"""Common functionals: linear, dropout, embedding, pad, interpolate…
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import rng
from ...framework.tensor import Tensor
from ...tensor._helper import apply, unwrap


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped [in, out] (paddle convention,
    reference: operators/matmul_v2_op.cc path of nn.Linear). Lowers to one MXU
    dot_general; bias-add fuses."""
    if bias is None:
        return apply(lambda v, w: jnp.matmul(v, w), x, weight, name="linear")
    return apply(lambda v, w, b: jnp.matmul(v, w) + b, x, weight, bias,
                 name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """reference: operators/dropout_op.cu. Keys come from the functional key
    scope under jit, else the global generator."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda v: v * (1.0 - p), x, name="dropout_infer")
        return x if isinstance(x, Tensor) else Tensor(x)
    key = rng.op_key()

    def f(v):
        if axis is None:
            mask_shape = v.shape
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            mask_shape = tuple(s if i in axes else 1
                               for i, s in enumerate(v.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply(f, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = rng.op_key()

    def f(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply(f, x, name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference: operators/lookup_table_v2_op.cu. On TPU a gather from the
    [vocab, dim] table; grads are dense (scatter-add), SelectedRows sparse
    grads are unnecessary under XLA."""
    def f(ids, w):
        pad = padding_idx
        if pad is not None and pad < 0:
            pad = w.shape[0] + pad   # paddle normalizes negative indices
        out = jnp.take(w, ids, axis=0)
        if pad is not None:
            out = jnp.where((ids == pad)[..., None], 0.0, out)
        return out

    return apply(f, x, weight, name="embedding")


def one_hot(x, num_classes, name=None):
    from ...tensor.creation import one_hot as _oh

    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(lbl, *rest):
        k = lbl.shape[-1]
        if rest:
            return (1 - epsilon) * lbl + epsilon * rest[0]
        return (1 - epsilon) * lbl + epsilon / k

    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(f, *args, name="label_smooth")


_PAD_MODES = {"constant": "constant", "reflect": "reflect",
              "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """reference: operators/pad3d_op.cc — paddle pad list is
    [left, right, top, bottom, front, back] over trailing spatial dims."""
    pad = [int(unwrap(p)) for p in pad] if not isinstance(pad, int) else pad

    def f(v):
        if isinstance(pad, int):
            cfg = [(pad, pad)] * v.ndim
        elif len(pad) == 2 * v.ndim:
            # full-tensor pad pairs, per-dim from first dim
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(v.ndim)]
        else:
            # spatial pad on trailing dims (NCHW => W then H then D order)
            cfg = [(0, 0)] * v.ndim
            spatial = list(range(v.ndim))
            if data_format.startswith("NC"):
                spatial = spatial[2:]
            else:
                spatial = spatial[1:-1]
            pairs = [(pad[2 * i], pad[2 * i + 1])
                     for i in range(len(pad) // 2)]
            # paddle orders pairs innermost-first (W,H,D); numpy wants per-axis
            for ax, pr in zip(reversed(spatial), pairs):
                cfg[ax] = pr
        kwargs = {"constant_values": value} if mode == "constant" else {}
        return jnp.pad(v, cfg, mode=_PAD_MODES[mode], **kwargs)

    return apply(f, x, name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """reference: operators/interpolate_v2_op.cc (bilinear/nearest/bicubic)."""
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "trilinear": "linear", "linear": "linear",
              "area": "linear"}[mode]

    def f(v):
        chan_last = not data_format.startswith("NC")
        spatial_idx = list(range(1, v.ndim - 1)) if chan_last else \
            list(range(2, v.ndim))
        if size is not None:
            tgt = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple))
                                            else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
                [scale_factor] * len(spatial_idx)
            tgt = [int(v.shape[ax] * s) for ax, s in zip(spatial_idx, sf)]
        out_shape = list(v.shape)
        for ax, s in zip(spatial_idx, tgt):
            out_shape[ax] = s
        if method == "nearest":
            return jax.image.resize(v, out_shape, "nearest")
        return jax.image.resize(v, out_shape, method)

    return apply(f, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply(f, *args, name="bilinear")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply(f, x1, x2, name="cosine_similarity")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(b, c // (r * r), h * r, w * r)
        b, h, w, c = v.shape
        v = v.reshape(b, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(b, h * r, w * r, c // (r * r))

    return apply(f, x, name="pixel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/unfold_op.cc, math/im2col.cc)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else \
        [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(v):
        b, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                patches.append(v[:, :, di:di + oh * st[0]:st[0],
                                 dj:dj + ow * st[1]:st[1]])
        out = jnp.stack(patches, axis=2)  # b, c, k*k, oh, ow
        return out.reshape(b, c * ks[0] * ks[1], oh * ow)

    return apply(f, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference: operators/fold_op would be the inverse of
    unfold_op.cc; Paddle exposes it as F.fold). Fold is *exactly* the
    linear transpose of unfold — overlapping patches sum — so rather than
    hand-writing the scatter-add we transpose the im2col map with
    jax.linear_transpose; XLA lowers it to the same scatter it would have
    gotten from autodiff, guaranteed adjoint-consistent with unfold."""
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else \
        [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else \
        [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(cols):
        b, ckk, length = cols.shape
        c = ckk // (ks[0] * ks[1])
        h, w = int(os_[0]), int(os_[1])

        def u(img):
            v = jnp.pad(img, [(0, 0), (0, 0), (pd[0], pd[0]),
                              (pd[1], pd[1])])
            oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
            ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
            patches = []
            for i in range(ks[0]):
                for j in range(ks[1]):
                    di, dj = i * dl[0], j * dl[1]
                    patches.append(v[:, :, di:di + oh * st[0]:st[0],
                                     dj:dj + ow * st[1]:st[1]])
            out = jnp.stack(patches, axis=2)
            return out.reshape(b, c * ks[0] * ks[1], oh * ow)

        img_spec = jax.ShapeDtypeStruct((b, c, h, w), cols.dtype)
        (img,) = jax.linear_transpose(u, img_spec)(cols)
        return img

    return apply(f, x, name="fold")
