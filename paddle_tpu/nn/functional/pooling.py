"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py;
kernels operators/math/pooling.cu). Lower to lax.reduce_window."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor._helper import apply
from .conv import _padding, _tuple


def _pool(x, kernel, stride, padding, n, data_format, reducer, init,
          ceil_mode=False, name="pool", average=False,
          exclusive=True):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad_cfg = _padding(padding, n)
    chan_last = not data_format.startswith("NC")

    def f(v):
        if chan_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = [(0, 0)] + (pad_cfg if isinstance(pad_cfg, list)
                               else [(0, 0)] * n) + [(0, 0)]
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = [(0, 0), (0, 0)] + (pad_cfg if isinstance(pad_cfg, list)
                                       else [(0, 0)] * n)
        if isinstance(pad_cfg, str):
            pads = pad_cfg
        out = jax.lax.reduce_window(v, init(v.dtype), reducer, dims, strides,
                                    pads)
        if average:
            if exclusive and not isinstance(pads, str):
                ones = jnp.ones(v.shape, v.dtype)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                               strides, pads)
                out = out / counts
            else:
                out = out / float(np.prod(kernel))
        return out

    return apply(f, x, name=name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.max,
                 lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating)
                 else jnp.iinfo(dt).min, ceil_mode, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.max,
                 lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating)
                 else jnp.iinfo(dt).min, ceil_mode, "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.max,
                 lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating)
                 else jnp.iinfo(dt).min, ceil_mode, "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, "avg_pool1d", average=True,
                 exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, "avg_pool2d", average=True,
                 exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add,
                 lambda dt: 0.0, ceil_mode, "avg_pool3d", average=True,
                 exclusive=exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _tuple(output_size, 2)

    def f(v):
        chan_last = not data_format.startswith("NC")
        hw_axes = (1, 2) if chan_last else (2, 3)
        # split each spatial dim into output_size regions and mean-reduce
        h, w = v.shape[hw_axes[0]], v.shape[hw_axes[1]]
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            # fast path: reshape + mean
            if chan_last:
                b, _, _, c = v.shape
                vv = v.reshape(b, oh, h // oh, ow, w // ow, c)
                return vv.mean(axis=(2, 4))
            b, c = v.shape[0], v.shape[1]
            vv = v.reshape(b, c, oh, h // oh, ow, w // ow)
            return vv.mean(axis=(3, 5))
        # general path via interpolation-style gather
        import jax

        return jax.image.resize(
            v, v.shape[:hw_axes[0]] + (oh, ow) + v.shape[hw_axes[1] + 1:],
            "linear")

    return apply(f, x, name="adaptive_avg_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    def f(v):
        b, c, l = v.shape
        o = int(output_size)
        if l % o == 0:
            return v.reshape(b, c, o, l // o).mean(axis=3)
        import jax

        return jax.image.resize(v, (b, c, o), "linear")

    return apply(f, x, name="adaptive_avg_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _tuple(output_size, 2)

    def f(v):
        b, c, h, w = v.shape
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            vv = v.reshape(b, c, oh, h // oh, ow, w // ow)
            return vv.max(axis=(3, 5))
        raise NotImplementedError(
            "adaptive_max_pool2d with non-divisible sizes")

    return apply(f, x, name="adaptive_max_pool2d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    def f(v):
        b, c, l = v.shape
        o = int(output_size)
        return v.reshape(b, c, o, l // o).max(axis=3)

    return apply(f, x, name="adaptive_max_pool1d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """reference: nn/functional/pooling.py adaptive_avg_pool3d (divisible
    sizes reshape-reduce; general sizes via per-region bounds)."""
    od, oh, ow = _tuple(output_size, 3)
    chan_last = not data_format.startswith("NC")

    def f(v):
        if chan_last:                      # NDHWC → pool in NCDHW layout
            v = v.transpose(0, 4, 1, 2, 3)
        b, c, d, h, w = v.shape
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            vv = v.reshape(b, c, od, d // od, oh, h // oh, ow, w // ow)
            out = vv.mean(axis=(3, 5, 7))
            return out.transpose(0, 2, 3, 4, 1) if chan_last else out
        import jax.numpy as jnp

        def pool_axis(vv, axis, n_out):
            size = vv.shape[axis]
            starts = (jnp.arange(n_out) * size) // n_out
            ends = ((jnp.arange(n_out) + 1) * size + n_out - 1) // n_out
            idx = jnp.arange(size)
            mask = (idx[None, :] >= starts[:, None]) & \
                (idx[None, :] < ends[:, None])
            mask = mask.astype(vv.dtype)
            mask = mask / mask.sum(axis=1, keepdims=True)
            # region-mean as a matmul over the pooled axis
            return jnp.moveaxis(
                jnp.tensordot(jnp.moveaxis(vv, axis, -1), mask.T,
                              axes=[[-1], [0]]), -1, axis)

        out = pool_axis(v, 2, od)
        out = pool_axis(out, 3, oh)
        out = pool_axis(out, 4, ow)
        return out.transpose(0, 2, 3, 4, 1) if chan_last else out

    return apply(f, x, name="adaptive_avg_pool3d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    od, oh, ow = _tuple(output_size, 3)

    def f(v):
        b, c, d, h, w = v.shape
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            vv = v.reshape(b, c, od, d // od, oh, h // oh, ow, w // ow)
            return vv.max(axis=(3, 5, 7))
        raise NotImplementedError(
            "adaptive_max_pool3d requires output_size to divide the "
            "spatial dims (general sizes: use adaptive_avg_pool3d)")

    return apply(f, x, name="adaptive_max_pool3d")
