"""General-op tail: CTR / ranking / text-matching / speech ops.

Reference kernels (paddle/fluid/operators/):
  nce_op.h, sample_logits_op.h, row_conv_op.cc, data_norm_op.cc,
  shuffle_channel_op.h, rank_loss_op.h, center_loss_op.h,
  im2sequence_op.h, lod_reset_op.h, pad_constant_like_op.h,
  unique_with_counts_op.h, partial_concat_op.h, partial_sum_op.h,
  match_matrix_tensor_op.cc, var_conv_2d_op.cc.

All dense compute is jittable jnp (class sampling for NCE/sample_logits
happens on host like the reference's CPU-pinned samplers, then the
gathered-logit math runs on device); unique_with_counts has a
data-dependent output size and executes on host (the reference kernel is
CPU-only for the same reason). LoD-carried ops follow the repo's
dense-ragged convention (explicit ``length`` tensors).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...tensor._helper import apply, unwrap

__all__ = [
    "nce", "sample_logits", "row_conv", "data_norm", "shuffle_channel",
    "rank_loss", "center_loss", "im2sequence", "lod_reset",
    "pad_constant_like", "unique_with_counts", "partial_concat",
    "partial_sum", "match_matrix_tensor", "var_conv_2d",
]

from ...core import rng as _core_rng

# host-side class-sampling stream; follows paddle.seed via the core.rng
# registry (persistent across calls — a fresh RandomState per call would
# redraw identical samples every training step)
_sample_rng = np.random.RandomState(0)
_core_rng.register_sample_rng(_sample_rng)


def shuffle_channel(x, group, name=None):
    """ShuffleNet channel shuffle (reference: shuffle_channel_op.h):
    [N, C, H, W] -> reshape C into (group, C/group), transpose, flatten."""
    g = int(group)

    def f(v):
        n, c, h, w = v.shape
        if c % g:
            raise ValueError(f"shuffle_channel: C={c} not divisible by "
                             f"group={g}")
        return v.reshape(n, g, c // g, h, w).swapaxes(1, 2) \
                .reshape(n, c, h, w)

    return apply(f, x, name="shuffle_channel")


def rank_loss(label, left, right, name=None):
    """Pairwise RankNet loss (reference: rank_loss_op.h):
    log(1 + exp(left-right)) - label*(left-right), elementwise."""
    def f(lbl, lo, ro):
        d = lo - ro
        # log(1+exp(d)) via softplus for stability
        return jax.nn.softplus(d) - lbl * d

    return apply(f, label, left, right, name="rank_loss")


def row_conv(input, filter, length=None, name=None):  # noqa: A002
    """Lookahead row convolution (DeepSpeech2; reference: row_conv_op.cc):
    out[t] = sum_{k<fc} x[t+k] * w[k] per channel, zero past each row's
    end. input [B, T, D] padded (+ ``length`` [B]), filter [fc, D]."""
    def f(v, w, lv=None):
        b, t, d = v.shape
        fc = w.shape[0]
        lens = (jnp.full((b,), t) if lv is None
                else lv.reshape(-1))
        tt = jnp.arange(t)
        mask = (tt[None, :] < lens[:, None])[..., None]
        vm = jnp.where(mask, v, 0.0)
        out = jnp.zeros_like(v)
        for k in range(fc):
            shifted = jnp.roll(vm, -k, axis=1)
            valid = (tt + k < t)[None, :, None]
            out = out + jnp.where(valid, shifted, 0.0) * w[k][None, None]
        return jnp.where(mask, out, 0.0)

    args = (input, filter) + (() if length is None else (length,))
    return apply(f, *args, name="row_conv")


def data_norm(x, batch_size, batch_sum, batch_square_sum, name=None):
    """CTR global-stats normalization (reference: data_norm_op.cc):
    means = sum/size, scales = sqrt(size/square_sum),
    y = (x - means) * scales. Returns (y, means, scales)."""
    def f(v, bn, bs, bss):
        means = bs / bn
        scales = jnp.sqrt(bn / bss)
        return (v - means[None, :]) * scales[None, :], means, scales

    return apply(f, x, batch_size, batch_sum, batch_square_sum,
                 name="data_norm")


def center_loss(x, label, centers, update_rate=0.5, need_update=True,
                name=None):
    """Center loss (face recognition; reference: center_loss_op.h):
    loss_i = ||x_i - c_{y_i}||^2 / 2, and (when need_update) the centers
    move toward their class means:
    c_k -= alpha * sum_i(diff_i [y_i=k]) / (1 + count_k).
    Returns (loss [B, 1], centers_out)."""
    def f(xv, lbl, cv):
        lbl = lbl.reshape(-1).astype(jnp.int32)
        diff = xv - cv[lbl]                        # [B, D]
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        if not need_update:
            return loss, cv
        acc = jnp.zeros_like(cv).at[lbl].add(diff)
        cnt = jnp.ones(cv.shape[0], xv.dtype).at[lbl].add(1.0)
        new_c = cv - update_rate * acc / cnt[:, None]
        return loss, new_c

    return apply(f, x, label, centers, name="center_loss")


def im2sequence(input, kernels, strides=(1, 1), paddings=(0, 0, 0, 0),  # noqa: A002
                name=None):
    """Image -> patch sequence (reference: im2sequence_op.h, fixed-size
    path): [N, C, H, W] -> [N*OH*OW, C*kh*kw], each row one kh x kw patch
    (channel-major like the reference's im2col). Returns (out,
    per-image sequence lengths [N])."""
    kh, kw = int(kernels[0]), int(kernels[1])
    sh, sw = int(strides[0]), int(strides[1])
    pu, pl, pd, pr = (int(p) for p in paddings)

    def f(v):
        n, c, h, w = v.shape
        vp = jnp.pad(v, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
        oh = (h + pu + pd - kh) // sh + 1
        ow = (w + pl + pr - kw) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                patches.append(jax.lax.slice(
                    vp, (0, 0, i, j),
                    (n, c, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                    (1, 1, sh, sw)))               # [N, C, OH, OW]
        # layout rows as (n, oh, ow) x cols (c, kh, kw)
        st = jnp.stack(patches, axis=2)            # [N, C, kh*kw, OH, OW]
        st = st.reshape(n, c, kh, kw, oh, ow)
        st = st.transpose(0, 4, 5, 1, 2, 3)        # [N, OH, OW, C, kh, kw]
        return st.reshape(n * oh * ow, c * kh * kw)

    out = apply(f, input, name="im2sequence")
    n, _, h, w = (int(s) for s in unwrap(input).shape)
    oh = (h + pu + pd - kh) // sh + 1
    ow = (w + pl + pr - kw) // sw + 1
    lens = Tensor(jnp.full((n,), oh * ow, jnp.int32))
    return out, lens


def lod_reset(x, y=None, target_lod=None, name=None):
    """Reassign sequence lengths (reference: lod_reset_op.h). In the
    dense-ragged convention LoD is carried as an explicit lengths
    tensor, so this op just validates and returns (x, new_lengths)."""
    if y is not None:
        new_lens = np.asarray(unwrap(y)).astype(np.int64).reshape(-1)
    elif target_lod is not None:
        offsets = np.asarray(target_lod, np.int64).reshape(-1)
        new_lens = np.diff(offsets)
    else:
        raise ValueError("lod_reset: either `y` (lengths) or `target_lod` "
                         "(offsets) is required")
    total = int(unwrap(x).shape[0])
    if int(new_lens.sum()) != total:
        raise ValueError(
            f"lod_reset: lengths sum {int(new_lens.sum())} != rows "
            f"{total}")
    return x, Tensor(jnp.asarray(new_lens))


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad ``y`` up to ``x``'s shape with a constant (reference:
    pad_constant_like_op.h; the grad of pad with batch-varying shapes)."""
    xs = tuple(int(s) for s in unwrap(x).shape)

    def f(yv):
        pads = [(0, xs[i] - yv.shape[i]) for i in range(yv.ndim)]
        if any(p[1] < 0 for p in pads):
            raise ValueError("pad_constant_like: y is larger than x")
        return jnp.pad(yv, pads, constant_values=pad_value)

    return apply(f, y, name="pad_constant_like")


def unique_with_counts(x, dtype="int32", name=None):
    """Unique values + index map + counts (reference:
    unique_with_counts_op.h; output size is data-dependent => host op,
    like the reference's CPU-only kernel). Returns (out, index, count):
    out = uniques in first-appearance order, index[i] = position of x[i]
    in out."""
    v = np.asarray(unwrap(x)).reshape(-1)
    uniq, first, inv, cnt = np.unique(v, return_index=True,
                                      return_inverse=True,
                                      return_counts=True)
    # np.unique sorts; reference keeps first-appearance order
    order = np.argsort(first, kind="stable")
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    idt = np.int32 if dtype in ("int32", np.int32) else np.int64
    return (Tensor(jnp.asarray(uniq[order])),
            Tensor(jnp.asarray(remap[inv].astype(idt))),
            Tensor(jnp.asarray(cnt[order].astype(idt))))


def partial_concat(x, start_index=0, length=-1, name=None):
    """Concat the same column slice of several [B, D] tensors
    (reference: partial_concat_op.h): out = concat([t[:, s:s+L] for t in
    x], axis=1)."""
    s = int(start_index)
    ln = int(length)

    def f(*vs):
        outs = []
        for v in vs:
            st = s if s >= 0 else v.shape[1] + s
            en = v.shape[1] if ln < 0 else st + ln
            outs.append(v[:, st:en])
        return jnp.concatenate(outs, axis=1)

    return apply(f, *x, name="partial_concat")


def partial_sum(x, start_index=0, length=-1, name=None):
    """Sum the same column slice of several [B, D] tensors (reference:
    partial_sum_op.h)."""
    s = int(start_index)
    ln = int(length)

    def f(*vs):
        out = None
        for v in vs:
            st = s if s >= 0 else v.shape[1] + s
            en = v.shape[1] if ln < 0 else st + ln
            sl = v[:, st:en]
            out = sl if out is None else out + sl
        return out

    return apply(f, *x, name="partial_sum")


def match_matrix_tensor(x, y, w, x_length=None, y_length=None, dim_t=None,
                        name=None):
    """Pyramid text-matching similarity cube (reference:
    match_matrix_tensor_op.cc): for each channel t,
    out[b, t, i, j] = x_bi . W_t . y_bj. Dense-ragged: x [B, LX, D],
    y [B, LY, D] padded with lengths; w [D, T, D]. Returns
    (out [B, T, LX, LY] masked to the valid extents, tmp = x.W)."""
    def f(xv, yv, wv, xl=None, yl=None):
        b, lx, d = xv.shape
        t = wv.shape[1]
        ly = yv.shape[1]
        # tmp[b, i, t, d2] = sum_d x[b,i,d] w[d,t,d2]
        tmp = jnp.einsum("bid,dte->bite", xv, wv)
        out = jnp.einsum("bite,bje->btij", tmp, yv)
        if xl is not None:
            mi = jnp.arange(lx)[None, :] < xl.reshape(-1)[:, None]
            out = jnp.where(mi[:, None, :, None], out, 0.0)
        if yl is not None:
            mj = jnp.arange(ly)[None, :] < yl.reshape(-1)[:, None]
            out = jnp.where(mj[:, None, None, :], out, 0.0)
        return out, tmp

    args = [x, y, w]
    if x_length is not None:
        args.append(x_length)
    if y_length is not None:
        if x_length is None:
            raise ValueError("match_matrix_tensor: y_length requires "
                             "x_length")
        args.append(y_length)
    return apply(f, *args, name="match_matrix_tensor")


def var_conv_2d(x, w, input_channel, output_channel, filter_size,
                stride=(1, 1), row_length=None, col_length=None,
                name=None):
    """Per-sample variable-extent 2D conv from the text-matching suite
    (reference: var_conv_2d_op.cc — each LoD row is an image of its own
    height/width). Dense-ragged: x [B, Cin, H, W] padded to the max
    extents with ``row_length``/``col_length`` [B]; valid region is
    convolved, output masked to each sample's own output extent."""
    kh, kw = (int(filter_size), int(filter_size)) \
        if np.isscalar(filter_size) else (int(filter_size[0]),
                                          int(filter_size[1]))
    sh, sw = (int(stride), int(stride)) if np.isscalar(stride) \
        else (int(stride[0]), int(stride[1]))

    def f(xv, wv, rl=None, cl=None):
        b, cin, h, wd = xv.shape
        # zero the pad region so it cannot leak into valid outputs
        if rl is not None:
            mr = jnp.arange(h)[None, :] < rl.reshape(-1)[:, None]
            xv = jnp.where(mr[:, None, :, None], xv, 0.0)
        if cl is not None:
            mc = jnp.arange(wd)[None, :] < cl.reshape(-1)[:, None]
            xv = jnp.where(mc[:, None, None, :], xv, 0.0)
        kernel = wv.reshape(output_channel, cin, kh, kw)
        out = jax.lax.conv_general_dilated(
            xv, kernel, (sh, sw), "VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        oh, ow = out.shape[2], out.shape[3]
        if rl is not None:
            orl = jnp.maximum((rl.reshape(-1) - kh) // sh + 1, 0)
            mr = jnp.arange(oh)[None, :] < orl[:, None]
            out = jnp.where(mr[:, None, :, None], out, 0.0)
        if cl is not None:
            ocl = jnp.maximum((cl.reshape(-1) - kw) // sw + 1, 0)
            mc = jnp.arange(ow)[None, :] < ocl[:, None]
            out = jnp.where(mc[:, None, None, :], out, 0.0)
        return out

    args = [x, w]
    if row_length is not None:
        args.append(row_length)
    if col_length is not None:
        if row_length is None:
            raise ValueError("var_conv_2d: col_length requires row_length")
        args.append(col_length)
    return apply(f, *args, name="var_conv_2d")


# ---------------------------------------------------------------------------
# sampled-softmax family (host sampling + device math, like the
# reference's CPU-pinned samplers feeding device matmuls)
# ---------------------------------------------------------------------------
def _log_uniform_sample(n_classes, shape, rng):
    """TF/reference LogUniformSampler: P(c) = log((c+2)/(c+1))/log(n+1)."""
    u = rng.rand(*shape)
    s = (np.exp(u * np.log(n_classes + 1.0)) - 1.0).astype(np.int64)
    return np.clip(s, 0, n_classes - 1)


def _sampler_prob(samples, n_classes, kind):
    if kind == "uniform":
        return np.full(samples.shape, 1.0 / n_classes, np.float32)
    return (np.log((samples + 2.0) / (samples + 1.0)) /
            np.log(n_classes + 1.0)).astype(np.float32)


def nce(input, label, weight, bias=None, num_total_classes=None,  # noqa: A002
        num_neg_samples=10, sampler="uniform", custom_dist=None,
        sample_weight=None, seed=0, name=None):
    """Noise-contrastive estimation loss (reference: nce_op.h).

    input [B, D], label [B, NT] int, weight [C, D], bias [C]. Per row:
    o_c = sigmoid(x.w_c + b_c); cost = sum over true classes of
    -log(o/(o+k*P(c))) plus sum over k sampled noise classes of
    -log(k*P(c)/(o+k*P(c))). Sampling happens on host (uniform /
    log_uniform / custom_dist, reference sampler types 0/1/2); the
    gathered-logit math is one jittable device expression. Returns
    cost [B, 1].
    """
    if num_total_classes is None:
        num_total_classes = int(unwrap(weight).shape[0])
    lbl = np.asarray(unwrap(label)).astype(np.int64).reshape(
        int(unwrap(input).shape[0]), -1)
    b, nt = lbl.shape
    k = int(num_neg_samples)
    rng = _sample_rng if seed == 0 else np.random.RandomState(seed)
    if sampler == "uniform":
        neg = rng.randint(0, num_total_classes, (b, k))
        pneg = _sampler_prob(neg, num_total_classes, "uniform")
        ptrue = _sampler_prob(lbl, num_total_classes, "uniform")
    elif sampler == "log_uniform":
        neg = _log_uniform_sample(num_total_classes, (b, k), rng)
        pneg = _sampler_prob(neg, num_total_classes, "log_uniform")
        ptrue = _sampler_prob(lbl, num_total_classes, "log_uniform")
    elif sampler == "custom_dist":
        dist = np.asarray(custom_dist, np.float64).reshape(-1)
        dist = dist / dist.sum()
        neg = rng.choice(num_total_classes, size=(b, k), p=dist)
        pneg = dist[neg].astype(np.float32)
        ptrue = dist[lbl].astype(np.float32)
    else:
        raise ValueError(f"nce: unknown sampler {sampler!r}")
    classes = np.concatenate([lbl, neg], axis=1)           # [B, NT+K]
    probs = np.concatenate([ptrue, pneg], axis=1)

    def f(xv, wv, *rest):
        bv = rest[0] if rest else None
        cw = wv[jnp.asarray(classes)]                      # [B, NT+K, D]
        logits = jnp.einsum("bd,bkd->bk", xv, cw)
        if bv is not None:
            logits = logits + bv[jnp.asarray(classes)]
        o = jax.nn.sigmoid(logits)
        bq = jnp.asarray(probs) * k
        cost_true = -jnp.log(o[:, :nt] / (o[:, :nt] + bq[:, :nt]) + 1e-20)
        cost_neg = -jnp.log(bq[:, nt:] / (o[:, nt:] + bq[:, nt:]) + 1e-20)
        out = cost_true.sum(axis=1) + cost_neg.sum(axis=1)
        if sample_weight is not None:
            out = out * jnp.asarray(unwrap(sample_weight)).reshape(-1)
        return out[:, None]

    args = (input, weight) + (() if bias is None else (bias,))
    return apply(f, *args, name="nce")


def sample_logits(logits, label, num_samples, remove_accidental_hits=True,
                  use_customized_samples=False, customized_samples=None,
                  customized_probabilities=None, seed=0, name=None):
    """Sampled-softmax helper (reference: sample_logits_op.h): gather
    logits at [true classes ++ sampled classes], subtract log Q(c)
    (the sampled-softmax correction), and mask "accidental hits"
    (sampled class == a true class) to -1e20. Returns (samples [B,NT+S],
    probabilities, sampled_logits, sampled_label [B,NT])."""
    lg = unwrap(logits)
    lbl = np.asarray(unwrap(label)).astype(np.int64)
    if lbl.ndim == 1:
        lbl = lbl[:, None]
    b, nt = lbl.shape
    n_classes = int(lg.shape[1])
    s = int(num_samples)
    if use_customized_samples:
        samples = np.asarray(unwrap(customized_samples)).astype(np.int64)
        probs = np.asarray(unwrap(customized_probabilities), np.float32)
    else:
        rng = _sample_rng if seed == 0 else np.random.RandomState(seed)
        neg = _log_uniform_sample(n_classes, (b, s), rng)
        samples = np.concatenate([lbl, neg], axis=1)
        probs = _sampler_prob(samples, n_classes, "log_uniform")
    hits = np.zeros(samples.shape, bool)
    if remove_accidental_hits:
        for i in range(b):
            true_set = set(lbl[i].tolist())
            for j in range(nt, samples.shape[1]):
                if int(samples[i, j]) in true_set:
                    hits[i, j] = True

    def f(lv):
        g = jnp.take_along_axis(lv, jnp.asarray(samples), axis=1)
        g = g - jnp.log(jnp.asarray(probs))
        return jnp.where(jnp.asarray(hits), -1e20, g)

    sampled = apply(f, logits, name="sample_logits")
    return (Tensor(jnp.asarray(samples)),
            Tensor(jnp.asarray(probs)),
            sampled,
            Tensor(jnp.broadcast_to(jnp.arange(nt, dtype=jnp.int32),
                                    (b, nt)).copy()))
