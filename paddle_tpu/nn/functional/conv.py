"""Convolution functionals (reference: python/paddle/nn/functional/conv.py;
CUDA kernels operators/conv_cudnn_op.cu). On TPU these lower to XLA
conv_general_dilated which tiles onto the MXU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v * n if len(v) == 1 else v))
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    # nested [[l, r], ...]
    return [tuple(int(q) for q in p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, transpose=False, output_padding=0, name="conv"):
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad_cfg = _padding(padding, n)
    chan_last = not data_format.startswith("NC")
    # jax dimension numbers: use NCHW-style regardless, transposing if needed.
    spatial = "".join(chr(ord("0") + i) for i in range(n))
    if chan_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn_args = (lhs_spec, rhs_spec, out_spec)

    def f(v, w, *rest):
        dn = jax.lax.conv_dimension_numbers(v.shape, w.shape, dn_args)
        if not transpose:
            out = jax.lax.conv_general_dilated(
                v, w, window_strides=stride, padding=pad_cfg,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups)
        else:
            # conv_transpose: gradient of forward conv — express via
            # lhs_dilation (fractional stride).
            opad = _tuple(output_padding, n)
            if isinstance(pad_cfg, str):
                raise ValueError("string padding unsupported for transpose")
            k = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n)]
            pads = [(k[i] - 1 - pad_cfg[i][0],
                     k[i] - 1 - pad_cfg[i][1] + opad[i]) for i in range(n)]
            # weight is [in, out/groups, *k] for transpose in paddle; flip
            # spatial dims and move to [out, in/groups, *k].
            if groups == 1:
                wt = jnp.swapaxes(jnp.flip(w, axis=tuple(range(2, 2 + n))),
                                  0, 1)
            else:
                ci, co_g = w.shape[0], w.shape[1]
                wt = w.reshape((groups, ci // groups, co_g) + w.shape[2:])
                wt = jnp.flip(wt, axis=tuple(range(3, 3 + n)))
                wt = jnp.swapaxes(wt, 1, 2)
                wt = wt.reshape((groups * co_g, ci // groups) + w.shape[2:])
            out = jax.lax.conv_general_dilated(
                v, wt, window_strides=(1,) * n, padding=pads,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out.ndim - 1 - n if chan_last else 1] = b.shape[0]
            if chan_last:
                shape = [1] * (out.ndim - 1) + [b.shape[0]]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f, *args, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, name="conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, name="conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, name="conv3d")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, transpose=True, output_padding=output_padding,
                 name="conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, transpose=True, output_padding=output_padding,
                 name="conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, transpose=True, output_padding=output_padding,
                 name="conv3d_transpose")
