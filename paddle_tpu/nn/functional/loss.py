"""Loss functionals (reference: python/paddle/nn/functional/loss.py;
kernels operators/softmax_with_cross_entropy_op.cu, bce_loss_op…)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply, unwrap


def _reduce_loss(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    """reference: softmax_with_cross_entropy_op.cu — fused
    log_softmax + nll in one traced fn so XLA emits the stable fused form."""
    def f(logits, lbl, *rest):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
            jnp.log(jnp.clip(logits, 1e-30, None))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            lbl_idx = lbl.astype(jnp.int32)
            squeeze = lbl_idx.ndim == logp.ndim
            if squeeze:
                lbl_idx = jnp.squeeze(lbl_idx, axis)
            # clip before gather so ignore_index (e.g. -100) can't wrap into
            # a real row via negative indexing; masked out below.
            mask = (lbl_idx != ignore_index)
            safe_idx = jnp.clip(lbl_idx, 0, logp.shape[axis] - 1)
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(safe_idx, axis), axis=axis)
            loss = jnp.squeeze(loss, axis)
            loss = jnp.where(mask, loss, 0.0)
            if rest:
                w = jnp.take(rest[0], safe_idx) * mask
                loss = loss * jnp.take(rest[0], safe_idx)
                if reduction == "mean":
                    return jnp.sum(jnp.where(mask, loss, 0.0)) / \
                        jnp.maximum(jnp.sum(w), 1e-12)
            elif reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(f, *args, name="cross_entropy")


softmax_with_cross_entropy = cross_entropy


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    def f(logp, lbl, *rest):
        loss = -jnp.take_along_axis(logp, lbl[:, None].astype(jnp.int32),
                                    axis=1)[:, 0]
        if rest:
            loss = loss * jnp.take(rest[0], lbl.astype(jnp.int32))
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(f, *args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce_loss(jnp.square(a - b), reduction),
                 input, label, name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(lambda a, b: _reduce_loss(jnp.abs(a - b), reduction),
                 input, label, name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce_loss(loss, reduction)

    return apply(f, input, label, name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    def f(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(f, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def f(z, y, *rest):
        i = 0
        w = None
        if weight is not None:
            w = rest[i]
            i += 1
        pw = rest[i] if pos_weight is not None else None
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight variant
        if pw is None:
            loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        else:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)

    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(f, *args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    def f(logp, y):
        loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)

    return apply(f, input, label, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return apply(lambda a, b, y: _reduce_loss(
        jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        input, other, label, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",  # noqa: A002
                         name=None):
    return apply(lambda x, y: _reduce_loss(
        jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)), reduction),
        input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(loss, reduction)

    return apply(f, input1, input2, label, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce_loss(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(f, input, positive, negative, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce_loss(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply(f, *args, name="sigmoid_focal_loss")


def square_error_cost(input, label):  # noqa: A002
    """reference: fluid.layers.square_error_cost"""
    return apply(lambda a, b: jnp.square(a - b), input, label,
                 name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return apply(lambda p, y: -y * jnp.log(p + epsilon)
                 - (1 - y) * jnp.log(1 - p + epsilon), input, label,
                 name="log_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: operators/warpctc_op.cc). Native JAX
    forward-algorithm implementation over lax.scan (no warpctc dylib)."""
    def f(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] log-probs; lbl: [B, S]
        T, B, C = lp.shape
        S = lbl.shape[1]
        # extended label seq: blank interleaved -> length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)
        ext_len = 2 * lbl_len + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = lp[0, jnp.arange(B), ext[:, 1]]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lbl_len > 0, first_lbl,
                                               neg_inf))

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            ext_shift2 = jnp.concatenate(
                [jnp.full((B, 2), -1, ext.dtype), ext[:, :-2]], 1)
            allow_skip = (ext != blank) & (ext != ext_shift2)
            merged = jnp.logaddexp(alpha, a_shift1)
            merged = jnp.where(allow_skip, jnp.logaddexp(merged, a_shift2),
                               merged)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def masked_step(carry, inp):
            alpha, t = carry
            lp_t = inp
            new_alpha, _ = step(alpha, lp_t)
            keep = (t < in_len)[:, None]
            return (jnp.where(keep, new_alpha, alpha), t + 1), None

        (alpha, _), _ = jax.lax.scan(masked_step, (alpha0, jnp.ones((), jnp.int32)),
                                     lp[1:])
        idx_last = jnp.clip(ext_len - 1, 0, 2 * S)
        idx_prev = jnp.clip(ext_len - 2, 0, 2 * S)
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0],
            jnp.take_along_axis(alpha, idx_prev[:, None], 1)[:, 0])
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len, 1))
        return _reduce_loss(loss, reduction)

    return apply(f, log_probs, labels, input_lengths, label_lengths,
                 name="ctc_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Dice coefficient loss over the last (class) axis (reference:
    fluid/layers/nn.py:7051 — one-hot the label, intersect per sample,
    1 − 2·inter/total, mean over batch)."""
    def f(x, lbl):
        if lbl.shape[-1] == 1:
            lbl = lbl.squeeze(-1)
        lv = jax.nn.one_hot(lbl, x.shape[-1], dtype=x.dtype)
        axes = tuple(range(1, x.ndim))
        inter = jnp.sum(x * lv, axes)
        denom = jnp.sum(x, axes) + jnp.sum(lv, axes)
        return jnp.mean(1.0 - 2.0 * inter / (denom + epsilon))

    return apply(f, input, label, name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference: fluid/layers/loss.py:1653 —
    same-label soft targets over the anchor·positiveᵀ similarity matrix
    plus Beta·l2_reg embedding regularization)."""
    def f(a, p, lbl):
        n = lbl.shape[0]
        lv = lbl.reshape(n, 1)
        soft = (lv == lv.T).astype(jnp.float32)
        soft = soft / jnp.sum(soft, 1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(jnp.square(a), 1))
              + jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25 * l2_reg
        sim = (a @ p.T).astype(jnp.float32)
        ce = -jnp.sum(soft * jax.nn.log_softmax(sim, -1), -1)   # [N]
        # the reference's reduce_sum(labels*ce, 0) -> reduce_mean
        # (loss.py:1714-1716) algebraically reduces to mean(ce) because
        # soft rows are normalized to sum to 1
        return l2 + jnp.mean(ce)

    return apply(f, anchor, positive, labels, name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss [N, 1] (reference: operators/
    hierarchical_sigmoid_op.h + math/matrix_bit_code.h SimpleCode: leaf
    id ``label + num_classes`` in an implicit heap, weight row =
    prefix − 1, binary target = suffix bit; sigmoid-CE summed over the
    path). ``path_table``/``path_code`` give the custom-tree variant;
    ``is_sparse`` is a storage hint (dense XLA gathers either way)."""
    if num_classes < 2 and path_table is None:
        raise ValueError("num_classes must be >= 2 for the default tree")

    def f(x, lbl, w, *rest):
        b = rest[0] if bias is not None else None
        if path_table is None:
            c = lbl.astype(jnp.int32) + num_classes
            max_len = int(num_classes).bit_length()
            js = jnp.arange(max_len)
            # step j is on the path iff the prefix above it is non-root:
            # c >> (j+1) > 0  (exact integer arithmetic — float log2
            # mis-rounds for class counts near 2^24)
            valid = (c[:, None] >> (js[None, :] + 1)) > 0     # [N, L]
            idx = jnp.where(valid, (c[:, None] >> (js[None, :] + 1)) - 1,
                            0)
            bit = ((c[:, None] >> js[None, :]) & 1).astype(x.dtype)
        else:
            pt, pc = rest[-2], rest[-1]
            idx = jnp.maximum(pt, 0).astype(jnp.int32)
            valid = pt >= 0
            bit = pc.astype(x.dtype)
        wrows = w[idx]                                        # [N, L, F]
        logits = jnp.einsum("nlf,nf->nl", wrows.astype(jnp.float32),
                            x.astype(jnp.float32))
        if b is not None:
            logits = logits + b.reshape(-1)[idx].astype(jnp.float32)
        ce = jnp.maximum(logits, 0) - logits * bit.astype(jnp.float32) \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(jnp.where(valid, ce, 0.0), -1,
                       keepdims=True).astype(x.dtype)

    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args += [path_table, path_code]
    return apply(f, *args, name="hsigmoid_loss")
