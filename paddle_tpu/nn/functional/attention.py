"""Attention functionals.

The reference has no fused training attention (SURVEY.md §5 long-context:
only inference-side multihead_matmul, operators/fused/multihead_matmul_op.cu).
Here attention is first-class: a reference jnp path plus a Pallas
flash-attention kernel (paddle_tpu.ops.flash_attention) selected
automatically for TPU-friendly shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """q,k,v: [batch, seq, heads, head_dim] (paddle convention).

    Uses the Pallas flash kernel when shapes allow, else the jnp path (which
    XLA still fuses reasonably well)."""
    from ...ops import flash_attention as fa

    use_flash = fa.supported(query.shape, attn_mask, dropout_p,
                             kv_seq=key.shape[1])
    if use_flash:
        return fa.flash_attention(query, key, value, causal=is_causal,
                                  scale=scale)

    def f(q, k, v, *rest):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / (d ** 0.5)
        # [B, S, H, D] -> [B, H, S, D]
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * s
        logits = logits.astype(jnp.float32)
        if is_causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), bool))
            logits = jnp.where(causal, logits, -1e30)
        if rest:
            m = rest[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, -1e30)
            else:
                logits = logits + m.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
        return jnp.swapaxes(out, 1, 2)

    args = (query, key, value) + ((attn_mask,) if attn_mask is not None
                                  else ())
    out = apply(f, *args, name="sdpa")
    if dropout_p > 0.0 and training:
        from .common import dropout

        out = dropout(out, dropout_p, training=training)
    return out
