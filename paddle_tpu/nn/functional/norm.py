"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
kernels operators/batch_norm_op.cu, layer_norm_op.cu). XLA fuses the
reduce+scale+shift chains; no hand-written welford kernels needed."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...tensor._helper import apply, unwrap


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(v):
        nrm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(nrm, epsilon)

    return apply(f, x, name="normalize")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    ns = (normalized_shape,) if isinstance(normalized_shape, int) else \
        tuple(normalized_shape)
    n_axes = len(ns)

    def f(v, *rest):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        # fp32 statistics even for bf16 activations (TPU numerics policy)
        vf = v.astype(jnp.float32)
        mean = jnp.mean(vf, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(vf - mean), axis=axes, keepdims=True)
        out = (vf - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(jnp.float32)
        return out.astype(v.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(f, *args, name="layer_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """reference: operators/batch_norm_op.cc. In training mode the running
    stats are updated in-place on the stats tensors (host-side mutation of
    the buffer value, like the reference's in-place MomentumTensor update)."""
    chan_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    xv = unwrap(x)
    reduce_axes = tuple(i for i in range(xv.ndim)
                        if i != (chan_axis % xv.ndim))
    if use_batch_stats:
        mean = jnp.mean(xv.astype(jnp.float32), axis=reduce_axes)
        var = jnp.var(xv.astype(jnp.float32), axis=reduce_axes)
        # update running stats (paddle: r = m*r + (1-m)*batch)
        running_mean._value = (momentum * running_mean._value
                               + (1 - momentum) * mean).astype(
                                   running_mean._value.dtype)
        running_var._value = (momentum * running_var._value
                              + (1 - momentum) * var).astype(
                                  running_var._value.dtype)
        mean_t, var_t = Tensor(mean), Tensor(var)
    else:
        mean_t, var_t = running_mean, running_var

    shape = [1] * xv.ndim
    shape[chan_axis] = xv.shape[chan_axis]

    def f(v, m, s, *rest):
        vf = v.astype(jnp.float32)
        out = (vf - m.reshape(shape)) / jnp.sqrt(
            s.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape).astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape).astype(jnp.float32)
        return out.astype(v.dtype)

    # In training, grads must flow through the batch statistics: recompute
    # them inside the traced fn so vjp sees them.
    if use_batch_stats:
        def g(v, *rest):
            vf = v.astype(jnp.float32)
            m = jnp.mean(vf, axis=reduce_axes)
            s = jnp.var(vf, axis=reduce_axes)
            out = (vf - m.reshape(shape)) / jnp.sqrt(s.reshape(shape) + epsilon)
            i = 0
            if weight is not None:
                out = out * rest[i].reshape(shape).astype(jnp.float32)
                i += 1
            if bias is not None:
                out = out + rest[i].reshape(shape).astype(jnp.float32)
            return out.astype(v.dtype)

        args = (x,) + tuple(t for t in (weight, bias) if t is not None)
        return apply(g, *args, name="batch_norm")

    args = (x, mean_t, var_t) + tuple(
        t for t in (weight, bias) if t is not None)
    return apply(f, *args, name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    chan_last = not data_format.startswith("NC")

    def f(v, *rest):
        # reduce over spatial dims only, per (batch, channel)
        axes = tuple(range(1, v.ndim - 1)) if chan_last else \
            tuple(range(2, v.ndim))
        vf = v.astype(jnp.float32)
        m = jnp.mean(vf, axis=axes, keepdims=True)
        s = jnp.var(vf, axis=axes, keepdims=True)
        out = (vf - m) / jnp.sqrt(s + eps)
        shape = [1] * v.ndim
        shape[-1 if chan_last else 1] = v.shape[-1 if chan_last else 1]
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out.astype(v.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(f, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(v, *rest):
        b = v.shape[0]
        if data_format == "NCHW":
            c = v.shape[1]
            vv = v.reshape((b, num_groups, c // num_groups) + v.shape[2:])
            axes = tuple(range(2, vv.ndim))
        else:
            c = v.shape[-1]
            vv = v.reshape(v.shape[:-1] + (num_groups, c // num_groups))
            axes = tuple(range(1, vv.ndim - 2)) + (vv.ndim - 1,)
        vf = vv.astype(jnp.float32)
        m = jnp.mean(vf, axis=axes, keepdims=True)
        s = jnp.var(vf, axis=axes, keepdims=True)
        out = ((vf - m) / jnp.sqrt(s + epsilon)).reshape(v.shape)
        shape = [1] * v.ndim
        shape[1 if data_format == "NCHW" else -1] = c
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out.astype(v.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(f, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(v):
        sq = jnp.square(v)
        half = size // 2
        c_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        pads = [(0, 0)] * v.ndim
        pads[c_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = sum(jnp.take(padded, jnp.arange(i, i + v.shape[c_axis]),
                           axis=c_axis) for i in range(size))
        return v / jnp.power(k + alpha * acc, beta)

    return apply(f, x, name="local_response_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm — beyond-reference op needed by modern LLM blocks."""
    def f(v, *rest):
        vf = v.astype(jnp.float32)
        ms = jnp.mean(jnp.square(vf), axis=-1, keepdims=True)
        out = vf / jnp.sqrt(ms + epsilon)
        if rest:
            out = out * rest[0].astype(jnp.float32)
        return out.astype(v.dtype)

    args = (x,) + ((weight,) if weight is not None else ())
    return apply(f, *args, name="rms_norm")
