"""Extension functionals: diag_embed, gather_tree (reference:
python/paddle/nn/functional/extension.py; kernels
operators/diag_embed_op.cc, operators/gather_tree_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply

__all__ = ["diag_embed", "gather_tree"]


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    """Embed the last dim of ``input`` as a diagonal of a new matrix
    spanning (dim1, dim2) (reference: nn/functional/extension.py
    diag_embed)."""
    def f(v):
        n = v.shape[-1]
        size = n + abs(offset)
        out_ndim = v.ndim + 1
        d1 = dim1 % out_ndim
        d2 = dim2 % out_ndim
        if d1 == d2:
            raise ValueError("dim1 and dim2 cannot be the same")
        base = jnp.zeros(v.shape[:-1] + (size, size), v.dtype)
        i = jnp.arange(n)
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        base = base.at[..., r, c].set(v)
        # the new matrix lives at the last two axes (row, col); place
        # row at dim1 and col at dim2
        return jnp.moveaxis(base, (-2, -1), (d1, d2))

    return apply(f, input, name="diag_embed")


def gather_tree(ids, parents):
    """Backtrack beam-search step outputs into full sequences
    (reference: operators/gather_tree_op.cc — walk parent pointers from
    the last step backwards). ids/parents: [max_time, batch, beam]."""
    def f(idv, pv):
        t, b, k = idv.shape
        beams = jnp.broadcast_to(jnp.arange(k, dtype=pv.dtype), (b, k))

        def step(carry, inp):
            beam = carry                       # [B, K] beam to follow
            id_t, par_t = inp
            tok = jnp.take_along_axis(id_t, beam, axis=1)
            parent = jnp.take_along_axis(par_t, beam, axis=1)
            return parent, tok

        _, toks = jax.lax.scan(step, beams, (idv[::-1], pv[::-1]))
        return toks[::-1]

    return apply(f, ids, parents, differentiable=False, name="gather_tree")
