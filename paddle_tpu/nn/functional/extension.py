"""Extension functionals: diag_embed, gather_tree (reference:
python/paddle/nn/functional/extension.py; kernels
operators/diag_embed_op.cc, operators/gather_tree_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply

__all__ = ["diag_embed", "gather_tree", "edit_distance"]


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    """Embed the last dim of ``input`` as a diagonal of a new matrix
    spanning (dim1, dim2) (reference: nn/functional/extension.py
    diag_embed)."""
    def f(v):
        n = v.shape[-1]
        size = n + abs(offset)
        out_ndim = v.ndim + 1
        d1 = dim1 % out_ndim
        d2 = dim2 % out_ndim
        if d1 == d2:
            raise ValueError("dim1 and dim2 cannot be the same")
        base = jnp.zeros(v.shape[:-1] + (size, size), v.dtype)
        i = jnp.arange(n)
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        base = base.at[..., r, c].set(v)
        # the new matrix lives at the last two axes (row, col); place
        # row at dim1 and col at dim2
        return jnp.moveaxis(base, (-2, -1), (d1, d2))

    return apply(f, input, name="diag_embed")


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference:
    operators/edit_distance_op.cc; python surface fluid/layers/nn.py
    edit_distance). Dense-ragged form: ``input``/``label`` are padded
    [B, T] int tensors with explicit lengths.

    The DP is expressed TPU-natively: the row recurrence
    new[j] = min(base_j, new[j-1]+1) is a min-plus prefix scan, so each
    row is one ``lax.cummin`` over ``base_k - k`` instead of a sequential
    inner loop — O(T) scan steps of vectorized work, vmapped over the
    batch. Returns (distance [B, 1] float32, sequence_num [1])."""
    import numpy as np

    from ...framework.tensor import Tensor

    a = np.asarray(input._value if hasattr(input, "_value") else input)
    b = np.asarray(label._value if hasattr(label, "_value") else label)
    if a.ndim == 1:
        a = a[None, :]
    if b.ndim == 1:
        b = b[None, :]
    la = (np.asarray(input_length._value if hasattr(input_length, "_value")
                     else input_length).reshape(-1).astype(np.int32)
          if input_length is not None
          else np.full((a.shape[0],), a.shape[1], np.int32))
    lb = (np.asarray(label_length._value if hasattr(label_length, "_value")
                     else label_length).reshape(-1).astype(np.int32)
          if label_length is not None
          else np.full((b.shape[0],), b.shape[1], np.int32))
    if ignored_tokens:
        # drop ignored tokens (host-side repack, like the reference's CPU
        # kernel preprocessing)
        def strip(arr, lens):
            rows, newl = [], []
            t = arr.shape[1]
            for r in range(arr.shape[0]):
                keep = [x for x in arr[r, :lens[r]]
                        if x not in ignored_tokens]
                newl.append(len(keep))
                rows.append(np.pad(np.asarray(keep, arr.dtype),
                                   (0, t - len(keep))))
            return np.stack(rows), np.asarray(newl, np.int32)

        a, la = strip(a, la)
        b, lb = strip(b, lb)

    tm, tn = a.shape[1], b.shape[1]

    def one(av, bv, m, n):
        js = jnp.arange(1, tn + 1)
        row0 = jnp.arange(tn + 1, dtype=jnp.int32)

        def step(carry, inp):
            row = carry
            tok, i = inp
            cost = (bv != tok).astype(jnp.int32)
            # beyond the label length the column is irrelevant; keep DP
            # well-formed anyway
            base = jnp.minimum(row[1:] + 1, row[:-1] + cost)
            adj = jnp.concatenate([i[None], base - js])
            new = jax.lax.cummin(adj) + jnp.arange(tn + 1)
            return new, new

        _, rows = jax.lax.scan(
            step, row0, (av, jnp.arange(1, tm + 1, dtype=jnp.int32)))
        table = jnp.concatenate([row0[None], rows], axis=0)
        return table[m, n].astype(jnp.float32)

    dist = jax.vmap(one)(jnp.asarray(a), jnp.asarray(b),
                         jnp.asarray(la), jnp.asarray(lb))
    if normalized:
        dist = dist / jnp.maximum(jnp.asarray(lb, jnp.float32), 1.0)
    return (Tensor(dist.reshape(-1, 1)),
            Tensor(jnp.asarray([a.shape[0]], jnp.int64)))


def gather_tree(ids, parents):
    """Backtrack beam-search step outputs into full sequences
    (reference: operators/gather_tree_op.cc — walk parent pointers from
    the last step backwards). ids/parents: [max_time, batch, beam]."""
    def f(idv, pv):
        t, b, k = idv.shape
        beams = jnp.broadcast_to(jnp.arange(k, dtype=pv.dtype), (b, k))

        def step(carry, inp):
            beam = carry                       # [B, K] beam to follow
            id_t, par_t = inp
            tok = jnp.take_along_axis(id_t, beam, axis=1)
            parent = jnp.take_along_axis(par_t, beam, axis=1)
            return parent, tok

        _, toks = jax.lax.scan(step, beams, (idv[::-1], pv[::-1]))
        return toks[::-1]

    return apply(f, ids, parents, differentiable=False, name="gather_tree")
