"""Activation functionals (reference: python/paddle/nn/functional/activation.py;
kernels operators/activation_op.cc/.cu). All lower to XLA elementwise ops that
fuse into surrounding MXU matmuls."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helper import apply, inplace_apply, make_unary, unwrap

relu = make_unary(jax.nn.relu, "relu")
relu6 = make_unary(lambda x: jnp.clip(x, 0.0, 6.0), "relu6")
sigmoid = make_unary(jax.nn.sigmoid, "sigmoid")
tanh = make_unary(jnp.tanh, "tanh")
softplus_ = jax.nn.softplus
silu = make_unary(jax.nn.silu, "silu")
swish = silu
mish = make_unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
tanhshrink = make_unary(lambda x: x - jnp.tanh(x), "tanhshrink")
log_sigmoid = make_unary(jax.nn.log_sigmoid, "log_sigmoid")


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), x,
                 name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), x,
                 name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size > 1:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v >= 0, v, w * v)

    return apply(f, x, weight, name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), x, name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                 x, name="selu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), x, name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply(lambda v: jnp.clip(v, min, max), x, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x,
                 name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(v > threshold, v - threshold,
                                     jnp.where(v < -threshold, v + threshold,
                                               0.0)), x, name="softshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), x,
                 name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x,
                 name="hardswish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda v: jnp.where(v * beta > threshold, v,
                                     jax.nn.softplus(v * beta) / beta), x,
                 name="softplus")


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x, name="softsign")


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, 0.0), x,
                 name="thresholded_relu")


def maxout(x, groups, axis=1, name=None):
    def f(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return apply(f, x, name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            v = v.astype(dtype)
        return jax.nn.softmax(v, axis=axis)

    return apply(f, x, name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            v = v.astype(dtype)
        return jax.nn.log_softmax(v, axis=axis)

    return apply(f, x, name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import rng

    key = rng.op_key()

    def f(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.take_along_axis(
                jnp.zeros_like(y), idx, axis=axis) * 0 + \
                (jnp.arange(y.shape[axis]).reshape(
                    [-1 if i == (axis % y.ndim) else 1
                     for i in range(y.ndim)]) == idx).astype(y.dtype)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply(f, x, name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    """Gated linear unit: split ``x`` in half along ``axis``,
    ``a * sigmoid(b)`` (reference: fluid/nets.py:335 composes split +
    sigmoid + elementwise_mul; one fused expression here)."""
    def f(xv):
        a, b = jnp.split(xv, 2, axis=axis)
        return a * jax.nn.sigmoid(b)

    return apply(f, x, name="glu")


def relu_(x, name=None):
    """Inplace relu (reference: paddle.nn.functional.relu_). Differentiable
    via tape rebinding like every inplace op here."""
    return inplace_apply(jax.nn.relu, x, name="relu_")


def elu_(x, alpha=1.0, name=None):
    """Inplace elu."""
    return inplace_apply(lambda v: jax.nn.elu(v, alpha), x, name="elu_")


def softmax_(x, axis=-1, dtype=None, name=None):
    """Inplace softmax."""
    def f(v):
        if dtype is not None:
            v = v.astype(dtype)
        return jax.nn.softmax(v, axis=axis)

    return inplace_apply(f, x, name="softmax_")


def tanh_(x, name=None):
    """Inplace tanh."""
    return inplace_apply(jnp.tanh, x, name="tanh_")
