"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class LayerNorm(Layer):
    """reference: nn/layer/norm.py LayerNorm → layer_norm_op.cu."""

    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """Beyond-reference: RMSNorm for LLM blocks."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm(_BatchNormBase):
    """fluid-era BatchNorm (reference: fluid/dygraph/nn.py BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """reference: nn/layer/norm.py SyncBatchNorm (sync_batch_norm_op.cu).

    Under pjit/GSPMD the batch statistics are computed over the global batch
    automatically when the batch axis is sharded — XLA inserts the
    all-reduce — so SyncBatchNorm ≡ BatchNorm in SPMD; kept for API parity.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub._num_features, sub._momentum,
                                    sub._epsilon,
                                    data_format=sub._data_format)
                if sub.weight is not None:
                    new.weight.set_value(sub.weight)
                if sub.bias is not None:
                    new.bias.set_value(sub.bias)
                new._mean.set_value(sub._mean)
                new._variance.set_value(sub._variance)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    pass


class InstanceNorm3D(InstanceNorm1D):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm: planned (round 2)")
