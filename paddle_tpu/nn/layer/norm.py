"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class LayerNorm(Layer):
    """reference: nn/layer/norm.py LayerNorm → layer_norm_op.cu."""

    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """Beyond-reference: RMSNorm for LLM blocks."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm(_BatchNormBase):
    """fluid-era BatchNorm (reference: fluid/dygraph/nn.py BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """reference: nn/layer/norm.py SyncBatchNorm (sync_batch_norm_op.cu).

    Under pjit/GSPMD the batch statistics are computed over the global batch
    automatically when the batch axis is sharded — XLA inserts the
    all-reduce — so SyncBatchNorm ≡ BatchNorm in SPMD; kept for API parity.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub._num_features, sub._momentum,
                                    sub._epsilon,
                                    data_format=sub._data_format)
                if sub.weight is not None:
                    new.weight.set_value(sub.weight)
                if sub.bias is not None:
                    new.bias.set_value(sub.bias)
                new._mean.set_value(sub._mean)
                new._variance.set_value(sub._variance)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    pass


class InstanceNorm3D(InstanceNorm1D):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral normalization: W / sigma_max(W) via power iteration
    (reference: operators/spectral_norm_op.cc; python surface
    fluid/dygraph/nn.py SpectralNorm). ``dim`` selects the axis treated as
    the output dim; the weight is viewed as [h, w] = [shape[dim],
    prod(rest)]. u/v are persistent buffers updated without gradient each
    forward; gradients flow through the weight only (matching the
    reference, which marks U/V as stop-gradient inputs)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        import numpy as np

        from ...core import rng

        self._dim = int(dim)
        self._power_iters = int(power_iters)
        self._eps = float(eps)
        self._weight_shape = [int(s) for s in weight_shape]
        h = self._weight_shape[self._dim]
        w = 1
        for i, s in enumerate(self._weight_shape):
            if i != self._dim:
                w *= s
        import jax

        ku, kv = jax.random.split(rng.op_key())
        u = jax.random.normal(ku, (h,), jnp.float32)
        v = jax.random.normal(kv, (w,), jnp.float32)
        self.register_buffer("weight_u", Tensor(
            u / jnp.maximum(jnp.linalg.norm(u), self._eps)))
        self.register_buffer("weight_v", Tensor(
            v / jnp.maximum(jnp.linalg.norm(v), self._eps)))

    def forward(self, weight):
        import jax

        from ...autograd.tape import apply

        def f(wt, u, v):
            perm = [self._dim] + [i for i in range(wt.ndim)
                                  if i != self._dim]
            mat = jnp.transpose(wt, perm).reshape(wt.shape[self._dim], -1)

            def normalize(x):
                return x / jnp.maximum(jnp.linalg.norm(x), self._eps)

            def it(carry, _):
                u_, v_ = carry
                m = jax.lax.stop_gradient(mat)
                v_ = normalize(m.T @ u_)
                u_ = normalize(m @ v_)
                return (u_, v_), None

            (u, v), _ = jax.lax.scan(it, (u, v), None,
                                     length=self._power_iters)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ (mat @ v)
            return wt / sigma, u, v

        out, u_new, v_new = apply(f, weight, self.weight_u, self.weight_v,
                                  name="spectral_norm")
        # power-iteration state persists across calls (buffer update, no
        # tape node — same as BatchNorm running stats)
        self.weight_u._value = u_new._value
        self.weight_v._value = v_new._value
        return out
