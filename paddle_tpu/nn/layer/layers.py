"""Layer — the module base class.

TPU-native analogue of the reference dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py; C++ side VarBase in
imperative/layer.h). Parameters are eager Tensors whose values live on
device as jax.Arrays; ``state_dict``/``set_state_dict`` match the reference
checkpoint contract.

The same Layer instance serves both eager execution and the compiled path:
``paddle_tpu.static.functional_call`` swaps parameter values for jit tracers,
so jax.jit / pjit trace straight through ``forward`` — the reference needed a
whole AST-translation subsystem (dygraph_to_static) for this.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import numpy as np

from ...core import dtype as dtype_mod
from ...framework.param_attr import ParamAttr
from ...framework.tensor import Parameter, Tensor
from .. import initializer as I

_name_counters = {}


def _unique_name(prefix: str) -> str:
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._full_name = _unique_name(
            name_scope or type(self).__name__.lower())

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if name in getattr(self, "_parameters", {}):
                del self._parameters[name]
            if name in getattr(self, "_sub_layers", {}):
                del self._sub_layers[name]
            if name in getattr(self, "_buffers", {}):
                if isinstance(value, Tensor):
                    self._buffers[name] = value
                    return
                del self._buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in (self._parameters, self._sub_layers, self._buffers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    # -- registration ------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        """reference: layers.py create_parameter + LayerHelper."""
        return build_parameter(shape, attr, dtype, is_bias,
                               default_initializer,
                               fallback_dtype=self._dtype)

    def create_tensor(self, name=None, persistable=None, dtype=None):
        import jax.numpy as jnp

        return Tensor(jnp.zeros([], dtype_mod.convert_dtype(dtype)
                                or self._dtype))

    # -- iteration ---------------------------------------------------------
    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=p, include_self=True)

    # -- mode --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle._id] = hook
        return handle

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            bare = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = owner._sub_layers[part]
            if bare not in owner._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                own[k].set_value(arr)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(d)
            for b in self.buffers():
                import jax.numpy as jnp

                if jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._value = b._value.astype(d)
            self._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ("\n  " + "\n  ".join(lines) + "\n") if lines else ""
        return f"{type(self).__name__}({extra}{body})"


class _HookRemoveHelper:
    _counter = 0

    def __init__(self, hooks_dict):
        self._hooks = hooks_dict
        self._id = _HookRemoveHelper._counter
        _HookRemoveHelper._counter += 1

    def remove(self):
        self._hooks.pop(self._id, None)


def build_parameter(shape, attr=None, dtype=None, is_bias=False,
                    default_initializer=None, name=None,
                    fallback_dtype="float32"):
    """Shared ParamAttr→Parameter resolution (Layer.create_parameter and
    static.create_parameter both delegate here so attr semantics cannot
    drift)."""
    attr = ParamAttr._to_attr(attr)
    if attr is None:
        return None
    dtype = dtype_mod.convert_dtype(dtype) or fallback_dtype
    init = attr.initializer or default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    from ...framework.lazy import in_lazy_mode

    if in_lazy_mode():
        import jax as _jax
        import numpy as _np

        value = _jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape), _np.dtype(dtype))
        p = Parameter(value, name=name or attr.name or
                      _unique_name("param"), trainable=attr.trainable)
        p._lazy_initializer = init
    else:
        value = init(shape, dtype)
        p = Parameter(value, name=name or attr.name or
                      _unique_name("param"), trainable=attr.trainable)
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p
