"""Seq2seq decoding API: Decoder / BeamSearchDecoder / dynamic_decode
(reference: python/paddle/fluid/layers/rnn.py:866,1581 — the Decoder
protocol the reference wires into a while_loop over LoDTensorArrays;
here the loop is a plain eager loop over jnp values, and the
transformer KV-cache path has its own compiled scan in ops/decoding.py).

The beam bookkeeping (scores, parent backtrack via gather_tree) follows
the reference's beam_search / beam_search_decode op pair
(operators/beam_search_op.cc, beam_search_decode_op.cc).
"""
from __future__ import annotations

import collections
import warnings

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...tensor._helper import unwrap
from .layers import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _map(fn, tree):
    return jax.tree_util.tree_map(fn, tree)


class Decoder:
    """Abstract decode protocol: initialize → step* → finalize
    (reference rnn.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over an RNN cell (reference rnn.py:866).

    ``embedding_fn`` maps token ids → cell inputs; ``output_fn`` maps
    cell outputs → vocab logits. Finished beams are held in place: all
    tokens except ``end_token`` score −inf so the beam keeps its score.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished",
                         "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam helpers (reference tile_beam_merge_with_batch et al.) -------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each batch row beam times)."""
        v = unwrap(x)
        out = jnp.repeat(v, beam_size, axis=0)
        return Tensor(out) if isinstance(x, Tensor) else out

    def _merge(self, v):
        """[B, beam, ...] -> [B*beam, ...]"""
        return v.reshape((-1,) + v.shape[2:])

    def _split(self, v):
        """[B*beam, ...] -> [B, beam, ...]"""
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    def initialize(self, initial_cell_states):
        cs = _map(unwrap, initial_cell_states)
        leaf = jax.tree_util.tree_leaves(cs)[0]
        batch = leaf.shape[0]
        k = self.beam_size
        cell_states = _map(
            lambda v: self._merge(jnp.broadcast_to(
                v[:, None], (batch, k) + v.shape[1:])), cs)
        # beam 0 active, others -inf so the first step seeds from beam 0
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (k - 1), jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, k), bool)
        lengths = jnp.zeros((batch, k), jnp.int32)
        tokens = jnp.full((batch * k,), self.start_token, jnp.int32)
        inputs = self.embedding_fn(Tensor(tokens)) if self.embedding_fn \
            else Tensor(tokens)
        return inputs, self.StateWrapper(cell_states, log_probs, finished,
                                         lengths), finished

    def step(self, time, inputs, states, **kwargs):
        k = self.beam_size
        cell_out, next_cs = self.cell(inputs, _map(Tensor,
                                                   states.cell_states))
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        logits = unwrap(logits).astype(jnp.float32)        # [B*beam, V]
        v = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, -1)
        step_lp = self._split(step_lp)                     # [B, beam, V]
        # finished beams: only end_token continues, at score 0
        noend = jnp.full((v,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(states.finished[..., None], noend[None, None],
                            step_lp)
        scores = states.log_probs[..., None] + step_lp     # [B, beam, V]
        flat = scores.reshape(scores.shape[0], -1)
        top, idx = jax.lax.top_k(flat, k)                  # [B, beam]
        parent = (idx // v).astype(jnp.int32)
        token = (idx % v).astype(jnp.int32)

        def gather_beam(x):
            s = self._split(x)
            g = jnp.take_along_axis(
                s, parent.reshape(parent.shape + (1,) * (s.ndim - 2)),
                axis=1)
            return self._merge(g)

        next_cs = _map(lambda t: gather_beam(unwrap(t)), next_cs)
        fin = jnp.take_along_axis(states.finished, parent, 1)
        lengths = jnp.take_along_axis(states.lengths, parent, 1)
        lengths = jnp.where(fin, lengths, lengths + 1)
        fin = fin | (token == self.end_token)
        next_states = self.StateWrapper(next_cs, top, fin, lengths)
        next_inputs = self.embedding_fn(Tensor(token.reshape(-1))) \
            if self.embedding_fn else Tensor(token.reshape(-1))
        out = self.OutputWrapper(top, token, parent)
        return out, next_states, next_inputs, fin

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers into whole sequences
        (reference beam_search_decode_op.cc → F.gather_tree)."""
        from ..functional.extension import gather_tree

        ids = gather_tree(Tensor(outputs.predicted_ids),
                          Tensor(outputs.parent_ids))
        return self.OutputWrapper(Tensor(outputs.scores), ids,
                                  Tensor(outputs.parent_ids)), final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every beam finishes or ``max_step_num``
    (reference rnn.py:1581). Eager loop (dygraph semantics); outputs are
    stacked over time — [time, ...] when ``output_time_major`` else
    batch-major."""
    if impute_finished:
        raise NotImplementedError(
            "impute_finished=True is not implemented; finished beams "
            "already hold their state via the decoder's finished mask.")
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    t = 0
    # Unbounded eager decode with an untrained cell can emit no end_token
    # ever; cap the default so it terminates instead of hanging (reference
    # rnn.py:1581 loops on a while-op with the same practical bound).
    limit = max_step_num if max_step_num is not None else 1000
    while t < limit:
        out, states, inputs, finished = decoder.step(t, inputs, states,
                                                     **kwargs)
        step_outputs.append(out)
        t += 1
        if bool(jnp.all(unwrap(finished))):
            break
    else:
        if max_step_num is None:
            warnings.warn(
                "dynamic_decode: no beam emitted end_token within the "
                "default 1000-step cap; pass max_step_num to raise it.")
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([unwrap(x) for x in xs], 0), *step_outputs)
    lengths = getattr(states, "lengths", None)
    final, final_states = decoder.finalize(stacked, states, lengths)

    def to_batch_major(x):
        v = unwrap(x)
        return Tensor(jnp.swapaxes(v, 0, 1)) if not output_time_major \
            else Tensor(v)

    final = jax.tree_util.tree_map(
        to_batch_major, final,
        is_leaf=lambda x: isinstance(x, (Tensor, jnp.ndarray)))
    if return_length:
        return final, final_states, Tensor(lengths)
    return final, final_states
