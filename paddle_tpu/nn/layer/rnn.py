"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py; CUDA path
cudnn_lstm_op.cu, CPU math/lstm_compute).

TPU-first: the whole time loop runs as one ``lax.scan`` inside a single
traced op, so eager mode pays one dispatch for the full sequence and the
compiled path gets an XLA-fused recurrence instead of per-step kernel
launches.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...tensor._helper import apply
from .. import initializer as I
from .layers import Layer


def _cell_math(mode):
    if mode == "LSTM":
        def step(x_proj, h, c, w_hh, b_hh):
            gates = x_proj + jnp.dot(h, w_hh.T) + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        return step
    if mode == "GRU":
        def step(x_proj, h, _c, w_hh, b_hh):
            h_proj = jnp.dot(h, w_hh.T) + b_hh
            xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
            hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return h_new, h_new

        return step

    def step(x_proj, h, _c, w_hh, b_hh, act=jnp.tanh):
        h_new = act(x_proj + jnp.dot(h, w_hh.T) + b_hh)
        return h_new, h_new

    return step


_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...tensor.creation import full

        b = batch_ref.shape[batch_dim_idx]
        return full([b, self.hidden_size], init_value,
                    dtype or batch_ref.dtype)


class SimpleRNNCell(RNNCellBase):
    mode = "RNN_TANH"

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        if activation == "relu":
            self.mode = "RNN_RELU"
        g = _GATES[self.mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [g * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [g * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [g * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [g * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        step = _cell_math(self.mode)

        def f(x, h, w_ih, w_hh, b_ih, b_hh):
            x_proj = jnp.dot(x, w_ih.T) + b_ih
            if self.mode.startswith("RNN"):
                return step(x_proj, h, None, w_hh, b_hh, act)[0]
            return step(x_proj, h, None, w_hh, b_hh)[0]

        h = apply(f, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    mode = "LSTM"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h0 = self.get_initial_states(inputs)
            states = (h0, h0)
        h, c = states
        step = _cell_math("LSTM")

        def f(x, hh, cc, w_ih, w_hh, b_ih, b_hh):
            x_proj = jnp.dot(x, w_ih.T) + b_ih
            return step(x_proj, hh, cc, w_hh, b_hh)

        h_new, c_new = apply(f, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    mode = "GRU"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        step = _cell_math("GRU")

        def f(x, h, w_ih, w_hh, b_ih, b_hh):
            x_proj = jnp.dot(x, w_ih.T) + b_ih
            return step(x_proj, h, None, w_hh, b_hh)[0]

        h = apply(f, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="gru_cell")
        return h, h


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrence over lax.scan."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        g = _GATES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                names = [f"weight_ih{sfx}", f"weight_hh{sfx}",
                         f"bias_ih{sfx}", f"bias_hh{sfx}"]
                self.add_parameter(names[0], self.create_parameter(
                    [g * hidden_size, in_sz], weight_ih_attr,
                    default_initializer=u))
                self.add_parameter(names[1], self.create_parameter(
                    [g * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u))
                self.add_parameter(names[2], self.create_parameter(
                    [g * hidden_size], bias_ih_attr, is_bias=True,
                    default_initializer=u))
                self.add_parameter(names[3], self.create_parameter(
                    [g * hidden_size], bias_hh_attr, is_bias=True,
                    default_initializer=u))
                self._param_names.append(names)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.mode == "LSTM"
        step = _cell_math(self.mode)
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        params = []
        for names in self._param_names:
            params.extend(self._parameters[n] for n in names)

        def f(x, *flat_params):
            v = x if self.time_major else jnp.swapaxes(x, 0, 1)  # [T,B,I]
            b = v.shape[1]
            hs, cs = [], []
            layer_in = v
            for layer in range(L):
                outs = []
                for d in range(D):
                    base = (layer * D + d) * 4
                    w_ih, w_hh, b_ih, b_hh = flat_params[base:base + 4]
                    seq = layer_in if d == 0 else jnp.flip(layer_in, 0)
                    x_proj = jnp.einsum("tbi,gi->tbg", seq, w_ih) + b_ih
                    h0 = jnp.zeros((b, H), v.dtype)
                    c0 = jnp.zeros((b, H), v.dtype)

                    def scan_fn(carry, xp):
                        h, c = carry
                        h2, c2 = step(xp, h, c, w_hh, b_hh)
                        return (h2, c2), h2

                    (hT, cT), out = jax.lax.scan(scan_fn, (h0, c0), x_proj)
                    if d == 1:
                        out = jnp.flip(out, 0)
                    outs.append(out)
                    hs.append(hT)
                    cs.append(cT)
                layer_in = jnp.concatenate(outs, -1) if D == 2 else outs[0]
            out = layer_in if self.time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(hs, 0)
            if is_lstm:
                return out, h_stack, jnp.stack(cs, 0)
            return out, h_stack

        res = apply(f, inputs, *params, name=self.mode.lower())
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class RNN(Layer):
    """Wraps a cell into a recurrence (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import stack

        seq_axis = 0 if self.time_major else 1
        steps = inputs.shape[seq_axis]
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        for i in idxs:
            x_t = inputs[(i,) if self.time_major else (slice(None), i)]
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, seq_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import concat

        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return concat([out_fw, out_bw], -1), (st_fw, st_bw)
