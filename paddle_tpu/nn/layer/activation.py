"""Activation layers — thin class wrappers over nn.functional
(reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _make(fname, cls_name, **fixed):
    fn = getattr(F, fname)

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = {**fixed, **kwargs}
            self._kwargs.pop("name", None)

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)

    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _make("relu", "ReLU")
ReLU6 = _make("relu6", "ReLU6")
Sigmoid = _make("sigmoid", "Sigmoid")
Tanh = _make("tanh", "Tanh")
GELU = _make("gelu", "GELU")
LeakyReLU = _make("leaky_relu", "LeakyReLU")
ELU = _make("elu", "ELU")
SELU = _make("selu", "SELU")
CELU = _make("celu", "CELU")
Hardtanh = _make("hardtanh", "Hardtanh")
Hardshrink = _make("hardshrink", "Hardshrink")
Softshrink = _make("softshrink", "Softshrink")
Hardsigmoid = _make("hardsigmoid", "Hardsigmoid")
Hardswish = _make("hardswish", "Hardswish")
Softplus = _make("softplus", "Softplus")
Softsign = _make("softsign", "Softsign")
Swish = _make("swish", "Swish")
Silu = _make("silu", "Silu")
Mish = _make("mish", "Mish")
Tanhshrink = _make("tanhshrink", "Tanhshrink")
ThresholdedReLU = _make("thresholded_relu", "ThresholdedReLU")
LogSigmoid = _make("log_sigmoid", "LogSigmoid")
Softmax = _make("softmax", "Softmax")
LogSoftmax = _make("log_softmax", "LogSoftmax")
Maxout = _make("maxout", "Maxout")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
