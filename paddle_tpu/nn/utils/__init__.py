"""paddle.nn.utils (reference: python/paddle/nn/utils/weight_norm_hook.py)."""
from .weight_norm_hook import remove_weight_norm, weight_norm  # noqa: F401

__all__ = ["weight_norm", "remove_weight_norm"]
