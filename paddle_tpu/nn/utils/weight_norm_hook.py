"""Weight normalization (reference: nn/utils/weight_norm_hook.py:155).

The reference installs a forward pre-hook recomputing ``weight`` from
(g, v) each call; here the same decomposition w = g * v/||v|| is
recomputed inside a forward wrapper **with tape-tracked tensor ops**, so
``loss.backward()`` reaches weight_g / weight_v (the recomputed weight
is a plain Tensor in ``__dict__`` — never re-registered as a
Parameter, so optimizers and state_dict see only g and v).
"""
from __future__ import annotations

from ...framework.tensor import Parameter, Tensor
from ...tensor import sqrt, square
from ...tensor import sum as tsum

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except(v: Tensor, dim) -> Tensor:
    if dim is None:
        return sqrt(tsum(square(v)))
    axes = [i for i in range(len(v.shape)) if i != dim]
    return sqrt(tsum(square(v), axis=axes, keepdim=True))


def weight_norm(layer, name="weight", dim=0):
    """Split ``layer.<name>`` into <name>_g (magnitude) and <name>_v
    (direction); forward recomputes the weight from them."""
    w = layer._parameters[name]
    p_v = Parameter(w._value)
    p_g = Parameter(_norm_except(p_v, dim)._value)
    del layer._parameters[name]
    layer.__dict__.pop(name, None)
    setattr(layer, name + "_g", p_g)
    setattr(layer, name + "_v", p_v)

    orig_forward = layer.forward

    def wrapped(*args, **kw):
        # tape-tracked recompute: grads flow to g and v through here
        w_t = p_v * (p_g / (_norm_except(p_v, dim) + 1e-12))
        setattr(layer, name, w_t)        # plain Tensor -> __dict__ only
        return orig_forward(*args, **kw)

    layer._wn_orig_forward = orig_forward
    layer._wn_name = name
    layer._wn_dim = dim
    layer.forward = wrapped
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold (g, v) back into a plain ``weight`` and restore forward."""
    if not hasattr(layer, "_wn_orig_forward"):
        return layer
    dim = layer._wn_dim
    p_v = layer._parameters.pop(name + "_v")
    p_g = layer._parameters.pop(name + "_g")
    layer.__dict__.pop(name + "_v", None)
    layer.__dict__.pop(name + "_g", None)
    layer.__dict__.pop(name, None)
    w = p_v * (p_g / (_norm_except(p_v, dim) + 1e-12))
    setattr(layer, name, Parameter(w._value))
    layer.forward = layer._wn_orig_forward
    del layer._wn_orig_forward
    return layer
