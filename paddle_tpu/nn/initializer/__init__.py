"""Weight initializers (reference: python/paddle/fluid/initializer.py,
python/paddle/nn/initializer/).

Functional: an Initializer maps (shape, dtype, key) -> jax array. The dygraph
layer calls them at Parameter creation; the functional path can call them
under jit with explicit keys (pure)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod
from ...core import rng


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"Unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weights are [in, out] in paddle convention.
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
        if key is None:
            key = rng.next_key()
        return self._init(tuple(int(s) for s in shape), dtype, key)

    def _init(self, shape, dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype, key):
        return (jax.random.normal(key, shape, jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype, key):
        out = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
        return (out * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _init(self, shape, dtype, key):
        return jax.random.uniform(key, shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _init(self, shape, dtype, key):
        arr = jnp.asarray(np.asarray(self.value), dtype).reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _init(self, shape, dtype, key):
        return (jax.nn.initializers.orthogonal(self.gain)(
            key, shape, jnp.float32)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def _init(self, shape, dtype, key):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + spatial_center] = 1.0
        return jnp.asarray(out, dtype)


# paddle legacy-name aliases (fluid.initializer)
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign
