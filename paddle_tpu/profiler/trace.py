"""Tracing layer: nested scopes that lower to the right mechanism per
execution regime.

Reference analogue: platform/profiler.h RecordEvent + the chrome-trace
export of profiler.proto. TPU-native translation (SURVEY §5: the host
never sees device op boundaries):

  - **inside a jit trace** a scope is pure metadata — ``jax.named_scope``
    prefixes every op traced under it, so XLA traces / HLO dumps attribute
    device time to the phase. Host timing a tracer would measure tracing,
    not execution, so no host span is recorded there.
  - **outside jit** (eager ops, dispatch, h2d staging, host pre/post) a
    scope is a ``perf_counter_ns`` span, nested via a thread-local stack,
    and doubles as ``jax.profiler.TraceAnnotation`` so the span also shows
    up inside a ``jax.profiler.start_trace`` device timeline.

Disabled mode is the fast path: ``scope()`` is a no-op context manager
guarded by one module-global bool — no allocation, no lock, no event.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

import jax

try:  # private jax API with a public-behavior contract (moe.py precedent)
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError:  # pragma: no cover - future jax renames
    def _trace_state_clean():
        return True


_enabled = False
_lock = threading.Lock()
_events: List[tuple] = []      # (full_name, start_ns, end_ns, thread_id)
# A million-step profiled fit must not grow host RAM without bound
# (Histogram's reservoir rule): the chrome-trace span list keeps the
# most recent _MAX_EVENTS, older ones are dropped (counted below) —
# scope_summary stays EXACT via the incremental _agg aggregates.
_MAX_EVENTS = 100_000
_dropped = 0
_agg: Dict[str, list] = {}     # name -> [count, total_ns, min_ns, max_ns]
_t_enable_ns: Optional[int] = None
_t_disable_ns: Optional[int] = None
_jax_trace_dir: Optional[str] = None


_live_stacks: Dict[int, List[str]] = {}   # thread id -> open scope names


def _prune_dead_stacks_locked() -> None:
    """Drop registrations of exited threads (_lock held). threading.local
    frees the per-thread value on thread death but this registry would
    keep a strong reference forever — per-epoch worker threads must not
    grow it without bound."""
    import sys

    alive = set(sys._current_frames())
    for tid in [t for t in _live_stacks if t not in alive]:
        del _live_stacks[tid]


class _TLS(threading.local):
    def __init__(self):
        self.stack: List[str] = []
        # registered so OTHER threads (the resilience step watchdog) can
        # see which scopes are open when a step hangs
        with _lock:
            _prune_dead_stacks_locked()
            _live_stacks[threading.get_ident()] = self.stack


_tls = _TLS()


def live_spans() -> Dict[int, List[str]]:
    """Currently-OPEN host scopes per thread id (the span stack a hung
    step is stuck inside). Only threads with at least one open scope are
    reported; empty when profiling is disabled (scopes no-op)."""
    with _lock:
        _prune_dead_stacks_locked()
        return {tid: list(s) for tid, s in _live_stacks.items() if s}


def is_enabled() -> bool:
    return _enabled


def enable(trace_dir: Optional[str] = None, reset: bool = True) -> None:
    """Turn profiling on. ``trace_dir`` additionally starts a jax/XLA
    device trace (TensorBoard-loadable) into that directory; host scopes
    ride along as TraceAnnotations."""
    global _enabled, _t_enable_ns, _t_disable_ns, _jax_trace_dir
    if reset:
        reset_events()
    _t_enable_ns = time.perf_counter_ns()
    _t_disable_ns = None
    if trace_dir:
        _jax_trace_dir = trace_dir
        jax.profiler.start_trace(trace_dir)
    _enabled = True


def disable() -> Dict[str, dict]:
    """Turn profiling off; returns the per-scope summary (scope_summary)."""
    global _enabled, _t_disable_ns, _jax_trace_dir
    _enabled = False
    _t_disable_ns = time.perf_counter_ns()
    if _jax_trace_dir:
        jax.profiler.stop_trace()
        _jax_trace_dir = None
    return scope_summary()


def reset_events() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _agg.clear()
        _dropped = 0


def enabled_window_s() -> float:
    """Seconds the profiler has been (was) enabled — the denominator for
    rate metrics (tokens/sec, steps/sec)."""
    if _t_enable_ns is None:
        return 0.0
    end = _t_disable_ns if _t_disable_ns is not None \
        else time.perf_counter_ns()
    return max(end - _t_enable_ns, 0) / 1e9


class scope:  # noqa: N801 - context manager, lowercase like jax.named_scope
    """``with profiler.scope("hybrid/fwd"):`` — see module docstring for
    the per-regime lowering. Nesting composes: host spans inherit the
    enclosing scopes' names ("step/h2d"), traced scopes nest via
    jax.named_scope's own stack."""

    __slots__ = ("name", "_t0", "_full", "_jax_ctx", "_mode")

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0
        self._full = name
        self._jax_ctx = None
        self._mode = 0  # 0: off, 1: host span, 2: named_scope

    def __enter__(self):
        if not _enabled:
            return self
        if not _trace_state_clean():
            # inside a jit/grad trace: metadata only
            self._mode = 2
            self._jax_ctx = jax.named_scope(self.name)
            self._jax_ctx.__enter__()
            return self
        self._mode = 1
        stack = _tls.stack
        self._full = "/".join(stack + [self.name]) if stack else self.name
        stack.append(self.name)
        self._jax_ctx = jax.profiler.TraceAnnotation(self._full)
        self._jax_ctx.__enter__()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._mode == 1:
            t1 = time.perf_counter_ns()
            if self._jax_ctx is not None:
                self._jax_ctx.__exit__(None, None, None)
            if _tls.stack and _tls.stack[-1] == self.name:
                _tls.stack.pop()
            global _dropped
            dt = t1 - self._t0
            with _lock:
                a = _agg.get(self._full)
                if a is None:
                    _agg[self._full] = [1, dt, dt, dt]
                else:
                    a[0] += 1
                    a[1] += dt
                    if dt < a[2]:
                        a[2] = dt
                    if dt > a[3]:
                        a[3] = dt
                _events.append((self._full, self._t0, t1,
                                threading.get_ident()))
                if len(_events) > _MAX_EVENTS:
                    drop = len(_events) - _MAX_EVENTS
                    del _events[:drop]
                    _dropped += drop
        elif self._mode == 2 and self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        self._mode = 0
        self._jax_ctx = None
        return False


class RecordEvent(scope):
    """RAII span under the reference's name (profiler.h:127): explicit
    ``begin()`` / ``end()`` in addition to the context-manager protocol."""

    def begin(self):
        return self.__enter__()

    def end(self):
        self.__exit__(None, None, None)


def annotate(name: str):
    """Pure device-side annotation: ALWAYS a ``jax.named_scope`` (zero
    runtime cost — op-name metadata only), independent of the enabled
    flag. Use inside jitted step functions so the phase names are baked
    into the compiled program whether or not profiling is on when the
    program is traced."""
    return jax.named_scope(name)


def events() -> List[tuple]:
    with _lock:
        return list(_events)


def scope_summary() -> Dict[str, dict]:
    """Per-scope host-span statistics: {full_name: {count, total_ms,
    mean_ms, min_ms, max_ms}} — from the incremental aggregates, so the
    numbers stay exact even after old spans age out of the bounded
    chrome-trace event list."""
    with _lock:
        items = [(name, list(a)) for name, a in _agg.items()]
    out = {}
    for name, (n, tot, mn, mx) in items:
        out[name] = {"count": n, "total_ms": round(tot / 1e6, 4),
                     "mean_ms": round(tot / n / 1e6, 4),
                     "min_ms": round(mn / 1e6, 4),
                     "max_ms": round(mx / 1e6, 4)}
    return out


def chrome_trace(extra_metadata: Optional[dict] = None) -> dict:
    """Collected host spans as a chrome://tracing / Perfetto-loadable
    object ({"traceEvents": [...]}); counters from the metrics registry
    ride along as metadata so one artifact carries the whole picture."""
    evs = events()
    trace_events = [
        {"name": n, "ph": "X", "ts": t0 / 1e3, "dur": (t1 - t0) / 1e3,
         "pid": 0, "tid": tid, "cat": "host"}
        for n, t0, t1, tid in evs]
    doc = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    meta = dict(extra_metadata or {})
    if _dropped:
        meta["dropped_events"] = _dropped
    doc["otherData"] = meta
    return doc


def export_chrome_trace(path: str,
                        extra_metadata: Optional[dict] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(extra_metadata), f)
    return path
