"""paddle_tpu.profiler — unified runtime observability.

Three always-on pieces ride alongside (ISSUE 8): per-request **event
timelines** + the **flight recorder** (events.py — serving lifecycle
edges, latency breakdowns, rolling TTFT/TPOT percentiles, post-mortem
dumps on watchdog fire/rollback), the **persistent metrics sink**
(sink.py — registry + event log as JSONL and a Prometheus textfile,
flushed on interval/exit/preempt/watchdog/rollback), and
**compiled-program accounting** (xla_stats.py — compile wall-time +
``cost_analysis()`` FLOPs/bytes per dispatch site, the inventory that
keys against recompile-telemetry names).

Device-time truth (ISSUE 11; device_trace.py): windowed
``jax.profiler.trace`` captures parsed with stdlib gzip+json into
per-op-category timings, per-collective measured durations (joined
with the byte accounting), a measured compute∩comm overlap fraction
(``phase/comm_traced_ms`` next to the apportioned
``phase/comm_measured_ms``), and a goodput/MFU ledger — via
``profiler.trace_capture`` / ``profiler.TraceWindow``,
``profile_step_phases(trace_window=k)``,
``ServingEngine.trace_window()`` and ``serve_bench --trace-window``.

Three pillars, one switch (``profiler.enable()``):

1. **Tracing** (``trace.py``): ``profiler.scope("name")`` /
   ``RecordEvent`` context managers. Inside a jit trace they lower to
   ``jax.named_scope`` (op-name metadata — device time attributable in
   XLA traces); outside they are host ``perf_counter`` spans that double
   as ``jax.profiler.TraceAnnotation``. Export: ``export_chrome_trace``
   (chrome://tracing JSON) and ``scope_summary`` per-scope stats.

2. **Metrics** (``metrics.py``): counters / gauges / histograms in a
   process-global registry — steps, tokens, per-phase ms, collective
   bytes, device-memory high-water marks. ``registry().aggregate()``
   reduces across ranks via distributed/fleet/metrics.py.

3. **Recompilation telemetry** (``recompile.py``): instrumented step
   functions report every jit (re)trace with the triggering abstract
   shapes; the ``profiler/retraces`` counter and ``retraces()`` log make
   silent recompiles in hybrid.py/pipeline.py visible.

Instrumented out of the box: ``HybridPipelineTrainer`` /
``HybridParallelTrainer`` steps (distributed/hybrid.py,
strategy_compiler.py), the pipeline schedule (named phases in the
compiled program), MoE dispatch/combine, ``hapi.Model`` train/eval
batches, and ``hapi.callbacks.ProfilerCallback`` for fit() loops. All
hooks are behind a single enabled check — disabled cost is one bool
read per step.

Async-step-pipeline signals (ISSUE 3; distributed/elastic.py): the
``hybrid/sync_wait`` span times every host←device loss materialization
(under deferred sync it shrinks toward zero — execution already
happened under later dispatches), ``elastic/loss_syncs`` counts them,
``elastic/prefetch_depth`` gauges how many staged batches the input
prefetcher had ready at each consume, and ``ckpt/stall_ms`` /
``ckpt/d2h_bytes`` account the checkpoint snapshot: stall_ms is ONLY
time the training loop was blocked (inline save + wait_snapshot gate),
so sync-vs-streamed saves are directly comparable.

Serving signals (ISSUE 4; paddle_tpu.serving): gauges
``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util`` (allocated fraction of the KV page pool),
``serving/decode_batch`` (slots advanced by the last tick) and
``serving/tokens_per_sec`` (set by ``ServingEngine.run``); counters
``serving/tokens_generated``, ``serving/prefills``, ``serving/ticks``,
``serving/preemptions``, ``serving/requests_finished`` and
``serving/token_syncs`` (host materializations of deferred tick
outputs); histogram ``serving/ttft_ms``; gauges
``serving/mixed_rows`` / ``serving/mixed_rows_decode`` /
``serving/mixed_rows_prefill`` (the prefill-vs-decode row mix of the
last unified tick). Per-shape executable caches (``GPT.generate``'s
jit cache, the Predictor's bucket executables, the paged-engine cache)
report LRU evictions as ``cache_evict/<name>``. The engine's ONE
hot-path program surfaces at the ``serving.tick#N`` recompile site and
must stay at one trace (``ServingEngine.compiled_sites``; the legacy
benchmarking mode adds ``serving.prefill#N``).

Quick use::

    import paddle_tpu.profiler as profiler
    profiler.enable()
    ... train ...
    print(profiler.summary())          # phases, rates, counters, retraces
    profiler.export_chrome_trace("trace.json")
    profiler.disable()
"""
from __future__ import annotations

from . import device_trace, events, instrument, metrics  # noqa: F401
from . import recompile, sink, trace, xla_stats  # noqa: F401
from . import disttrace, live, sketch  # noqa: F401
from .live import AlertRule, LiveAggregator, default_rules  # noqa: F401
from .sketch import QuantileSketch  # noqa: F401
from .disttrace import ClockSync, clock_state  # noqa: F401
from .disttrace import set_clock_state, trace_id  # noqa: F401
from .device_trace import TraceWindow, last_trace_summary  # noqa: F401
from .device_trace import trace_capture  # noqa: F401
from .events import (EventLog, FlightRecorder, dump_flight,  # noqa: F401
                     emit, flight_recorder, latency_breakdown,
                     latency_table, request_latency_stats)
from .events import log as event_log  # noqa: F401
from .instrument import (collective_stats, device_memory_stats,  # noqa: F401
                         estimate_comm_ms, record_collective_stats,
                         record_collectives_from, record_memory_high_water,
                         record_memory_ledger, record_phases,
                         tokens_in_batch)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      registry)
from .recompile import (mark_trace, retraces, suppressed,  # noqa: F401
                        trace_counts, unique_site, watch)
from .sink import (MetricsSink, active_sink, disable_sink,  # noqa: F401
                   enable_sink, flush_active, prometheus_text)
from .trace import (RecordEvent, annotate, chrome_trace,  # noqa: F401
                    export_chrome_trace, is_enabled, live_spans, scope,
                    scope_summary)
from .xla_stats import program_inventory, record_compiled  # noqa: F401
from .xla_stats import record_lowered  # noqa: F401

__all__ = [
    "enable", "disable", "is_enabled", "reset",
    "scope", "RecordEvent", "annotate",
    "scope_summary", "chrome_trace", "export_chrome_trace", "live_spans",
    "registry", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "mark_trace", "watch", "retraces", "trace_counts", "suppressed",
    "unique_site",
    "collective_stats", "record_collective_stats",
    "record_collectives_from", "estimate_comm_ms",
    "record_phases", "device_memory_stats", "record_memory_high_water",
    "record_memory_ledger", "tokens_in_batch",
    "summary",
    # per-request event timelines + flight recorder (events.py)
    "emit", "event_log", "EventLog", "latency_breakdown", "latency_table",
    "request_latency_stats", "flight_recorder", "FlightRecorder",
    "dump_flight",
    # persistent metrics sink (sink.py)
    "MetricsSink", "enable_sink", "disable_sink", "active_sink",
    "flush_active", "prometheus_text",
    # compiled-program accounting (xla_stats.py)
    "record_lowered", "record_compiled", "program_inventory",
    # parsed XLA trace windows (device_trace.py)
    "trace_capture", "TraceWindow", "last_trace_summary",
    # cross-host request tracing (disttrace.py, ISSUE 14)
    "trace_id", "clock_state", "set_clock_state", "ClockSync",
    # live mesh telemetry plane (sketch.py / live.py, ISSUE 16)
    "QuantileSketch", "LiveAggregator", "AlertRule", "default_rules",
]


def enable(trace_dir=None, reset: bool = True) -> None:
    """Turn profiling on. ``reset`` (default) clears prior host spans,
    the metrics registry, the event log, the program inventory, and the
    public retrace log, so the window's counters and rates cover only
    this session; retrace signature HISTORY is kept (a step function
    first traced before enable must still read as a retrace on its next
    re-trace), and event SEQUENCE NUMBERS are kept (an active sink's
    cursor survives the reset). ``trace_dir`` additionally starts a
    jax/XLA device trace into that directory."""
    if reset:
        # an active sink drains the ring first — a reset must not eat
        # events the sink promised to persist exactly once
        sink.flush_active("reset")
        trace.reset_events()
        metrics.registry().reset()
        recompile.clear_log()
        events.log().clear()
        xla_stats.reset()
        device_trace.reset()
    trace.enable(trace_dir=trace_dir, reset=False)


def disable() -> dict:
    """Stop profiling; returns the full summary()."""
    s = summary()
    trace.disable()
    return s


def reset() -> None:
    """Clear spans, metrics, events, the program inventory, and retrace
    history (enabled flag and event sequence numbers kept; an active
    sink drains the event ring before it empties)."""
    sink.flush_active("reset")
    trace.reset_events()
    metrics.registry().reset()
    recompile.reset()
    events.log().clear()
    xla_stats.reset()
    device_trace.reset()


def summary(aggregate: bool = False) -> dict:
    """One JSON-ready dict with everything this subsystem observed:
    per-scope host spans, metric snapshot (rank-aggregated when
    ``aggregate``), derived rates (tokens/sec, steps/sec over the enabled
    window), per-phase ms gauges, and the retrace log. Also surfaces
    IN-PROCESS what used to be visible only post-mortem in
    metrics.jsonl: ``events_lost`` (lifecycle events aged out of the
    bounded ring — a truncated timeline is a fact about THIS process,
    not just the sink's file) and ``sink`` health (flush count, failed
    flushes, last error)."""
    reg = metrics.registry()
    snap = reg.aggregate() if aggregate else reg.snapshot()
    window_s = trace.enabled_window_s()
    rates = {}
    phases = {}
    for name, s in snap.items():
        if s["type"] == "counter" and window_s > 0 and \
                name.startswith("train/"):
            rates[name.split("/", 1)[1] + "_per_sec"] = round(
                s["value"] / window_s, 3)
        if name.startswith("phase/") and s.get("value") is not None:
            phases[name.split("/", 1)[1]] = round(s["value"], 4)
    return {"enabled_window_s": round(window_s, 6),
            "scopes": trace.scope_summary(),
            "metrics": snap,
            "rates": rates,
            "phases_ms": phases,
            "retraces": recompile.retraces(),
            "programs": xla_stats.inventory(),
            "events_lost": events.log().dropped,
            "sink": sink.stats()}
