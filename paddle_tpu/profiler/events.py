"""Per-request event timelines + the flight recorder.

The metrics registry answers "how much / how fast overall"; this module
answers "what happened to request 17, when". It is a bounded structured
event log: monotonic-timestamped typed records that the serving engine
feeds at every request lifecycle edge (submit, admit, prefix-hit, COW,
chunk dispatch, first token, preempt, requeue, finish) and the
resilience runner feeds at rollback. From the log this module derives:

- **per-request timelines** (``timeline(rid)``) and a latency
  breakdown (``latency_breakdown``): queue wait / prefill / decode /
  preempted time, reconstructed by a state machine over the edges;
- **rolling-window TTFT/TPOT percentiles**
  (``request_latency_stats(window_s=...)``) from ``finish`` events,
  which carry ``ttft_ms``/``tpot_ms`` attributes stamped by the engine
  — per-workload p50/p90/p95/p99, not just whole-run histograms;
- the **flight recorder** (``FlightRecorder`` / ``dump_flight``): a
  post-mortem artifact — the tail of the event ring, the current
  metrics snapshot, metric DELTAS since the last ``mark()``, and the
  profiler's open spans — written when the watchdog fires or the
  bad-step guard rolls back, so a hang or rollback leaves evidence
  instead of nothing.

Design rules:

- The log is ALWAYS ON by default (``set_enabled``): lifecycle edges
  are rare next to decode ticks (a request emits O(1) events per
  residency period, never per token), so the hot loop pays one bool
  read plus an occasional lock-append. serve_bench measures the
  overhead explicitly.
- Bounded ring: the deque keeps the most recent ``capacity`` events;
  older ones are dropped and counted (``dropped``) — the Histogram
  reservoir rule. Sequence numbers are monotonic FOREVER (``clear()``
  empties the buffer but never rewinds ``next_seq``), so a sink cursor
  survives resets.
- Helpers never raise out of post-mortem paths: ``dump_flight``
  swallows I/O errors and returns None — a diagnostic must not take
  the job down (watchdog.dump_stacks rule).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Event", "EventLog", "log", "emit", "set_enabled", "is_enabled",
    "timeline", "latency_breakdown", "breakdown_from_events",
    "latency_table", "request_latency_stats",
    "FlightRecorder", "flight_recorder", "dump_flight",
]

#: well-known serving lifecycle kinds (informational — emit() accepts
#: any string; the resilience runner adds "rollback"). Speculative
#: decoding (ISSUE 9) adds three: ``draft`` (the slot's draft-cache
#: catch-up began — once per admission cycle), ``verify`` (the first
#: verify tick carrying the request's drafts — once per cycle), and
#: ``accept`` (per speculating verify tick, attrs ``accepted`` /
#: ``drafted``). ``accept`` is the one deliberately-denser kind:
#: bounded by verify ticks, i.e. at most one event per ~k emitted
#: tokens, and only ever emitted by a spec-enabled engine — plain
#: engines keep the strict O(1)-per-residency lifecycle rate.
#: Disaggregated serving (ISSUE 13) adds two lifecycle-edge kinds:
#: ``handoff_out`` (a prefill-group engine exported a held request's
#: KV pages — attrs ``tokens``/``pages``/``bytes``/``ms``, the span
#: duration of the export work) and ``handoff_in`` (a decode-group
#: engine imported them — same attrs). Both are O(1) per request.
#: Cross-host tracing (ISSUE 14) adds control-plane kinds, all rare
#: (per agreement round / per routed request, never per token):
#: ``route`` (an admission assignment was adopted — attrs ``gid``,
#: ``prefill``, ``decode``, ``trace``), ``clock_sync`` (the mesh's
#: clock-offset agreement published — attrs ``offset_s``/``unc_s``/
#: ``ref``), ``consensus_decision`` (this rank adopted an epoch —
#: attrs ``family``/``epoch``/``leader``/``missing``, plus ``rtt_ms``
#: when this rank voted in it), ``lease_expiry`` (a peer's lease went
#: stale — attr ``peer``) and ``vote_window_expiry`` (the leader
#: published without every live vote — attrs ``family``/``epoch``/
#: ``waiting_on``). Any event of a request that carries a trace id
#: additionally bears a ``trace`` attr — the cross-host join key
#: tools/merge_traces.py stitches on.
#: Live telemetry (ISSUE 16) adds ``alert``: an AlertRule transition
#: in profiler/live.py — attrs ``rule``/``state`` (``firing`` or
#: ``resolved``), ``value``/``threshold`` when the rule is numeric.
#: Rare by construction (one per rule TRANSITION, hysteresis-damped,
#: never per tick).
#: The elastic mesh (ISSUE 17) adds four control-plane kinds, all
#: O(1) per membership change or per orphaned request, never per
#: token: ``member_join`` / ``member_leave`` (a consensus membership
#: round admitted or evicted a rank — attrs ``member``/``role``/
#: ``epoch``, plus ``reason`` on leave), ``redispatch`` (a dead
#: rank's orphaned request was reconstructed and re-dispatched —
#: attrs ``gid``/``trace``/``mode`` (``requeue`` = back through
#: ``route_requests`` for a fresh prefill, ``reprefill`` = the decode
#: owner re-prefills locally, ``scavenge`` = a surviving exported-KV
#: file was claimed and reused) and ``dead_rank``), and ``cancel``
#: (the engine abandoned a request without a result — orphan
#: bookkeeping when a re-dispatched gid's stale local work is torn
#: down; attr ``reason``).
EVENT_KINDS = (
    "submit", "admit", "prefix_hit", "cow_copy", "chunk",
    "first_token", "draft", "verify", "accept",
    "handoff_out", "handoff_in",
    "route", "clock_sync", "consensus_decision", "lease_expiry",
    "vote_window_expiry",
    "member_join", "member_leave", "redispatch", "cancel",
    "preempt", "requeue", "finish", "rollback", "alert",
)


class Event:
    """One structured record: process-monotonic ``t_ns``
    (perf_counter_ns — the same clock as trace.py spans), a ``kind``
    string, an optional request id, and free-form attrs."""

    __slots__ = ("seq", "t_ns", "kind", "rid", "attrs")

    def __init__(self, seq: int, t_ns: int, kind: str,
                 rid: Optional[int], attrs: dict):
        self.seq = seq
        self.t_ns = t_ns
        self.kind = kind
        self.rid = rid
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {"seq": self.seq, "t_ns": self.t_ns, "kind": self.kind}
        if self.rid is not None:
            d["rid"] = self.rid
        d.update(self.attrs)
        return d

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Event({self.to_dict()!r})"


class EventLog:
    """Bounded, thread-safe, seq-numbered ring of Events."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf: deque = deque()
        self._lock = threading.Lock()
        self._next_seq = 0
        self._dropped = 0

    def emit(self, kind: str, rid: Optional[int] = None,
             **attrs) -> Optional[Event]:
        if not _enabled:
            return None
        t = time.perf_counter_ns()
        with self._lock:
            ev = Event(self._next_seq, t, kind, rid, attrs)
            self._next_seq += 1
            self._buf.append(ev)
            if len(self._buf) > self.capacity:
                self._buf.popleft()
                self._dropped += 1
        return ev

    @property
    def next_seq(self) -> int:
        return self._next_seq

    @property
    def total(self) -> int:
        """Events ever emitted (including ones aged out of the ring)."""
        return self._next_seq

    @property
    def dropped(self) -> int:
        return self._dropped

    def events(self, rid: Optional[int] = None,
               kind: Optional[str] = None,
               since_seq: int = 0) -> List[Event]:
        with self._lock:
            evs = list(self._buf)
        return [e for e in evs
                if e.seq >= since_seq
                and (rid is None or e.rid == rid)
                and (kind is None or e.kind == kind)]

    def since(self, seq: int) -> Tuple[List[Event], int]:
        """(events with seq >= seq, next cursor) — the sink's segment
        read. The cursor advances past everything returned, so repeated
        calls stream the log exactly once."""
        with self._lock:
            evs = [e for e in self._buf if e.seq >= seq]
            return evs, self._next_seq

    def tail(self, n: int) -> List[Event]:
        with self._lock:
            if n <= 0:
                return []
            return list(self._buf)[-n:]

    def clear(self) -> None:
        """Empty the buffer. Sequence numbers are NOT rewound (sink
        cursors stay valid); the dropped counter is reset."""
        with self._lock:
            self._buf.clear()
            self._dropped = 0


_enabled = True
_log = EventLog()


def log() -> EventLog:
    return _log


def emit(kind: str, rid: Optional[int] = None, **attrs) -> Optional[Event]:
    """Emit into the process-global log (the one instrumented code
    feeds and the sink drains)."""
    return _log.emit(kind, rid=rid, **attrs)


def set_enabled(on: bool) -> None:
    """Event recording on/off (default ON — lifecycle edges are cheap).
    serve_bench flips this to measure the overhead honestly."""
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# timeline queries
# ---------------------------------------------------------------------------
def timeline(rid: int, event_log: Optional[EventLog] = None) -> List[Event]:
    """All of ``rid``'s events in emission order. NOTE: rids are unique
    within one engine; when several engines share the process, filter
    by the ``eng`` attr (each engine stamps its own id) or use
    ``latency_table`` which groups by (eng, rid)."""
    return (event_log or _log).events(rid=rid)


#: state -> breakdown bucket the elapsed time is charged to
_STATE_BUCKET = {"queued": "queue_wait_ms", "requeued": "preempted_ms",
                 "prefill": "prefill_ms", "decode": "decode_ms",
                 "re_prefill": "preempted_ms"}


def breakdown_from_events(evs: List[Event]) -> Optional[dict]:
    """Latency breakdown of ONE request's event sequence: wall time
    split into queue wait (submit -> first admission), prefill
    (admission -> first token), decode (first token -> finish) and
    preempted time (each preempt -> the END of the re-prefill it
    forced: requeue wait, re-admission, and the re-prefill chunks —
    tracked via the ``final`` attr the engine stamps on ``chunk``
    events — are all preemption cost, not decode), plus the finish
    event's ttft/tpot/tokens attrs. Speculative-decoding events ride
    the decode bucket: ``draft``/``verify``/``accept`` never move the
    state machine (time keeps accruing to the current state, so the
    four buckets still sum to the total); ``accept`` events are instead
    FOLDED into ``spec_accepted``/``spec_drafted`` counts on the
    result (present only when the request speculated). Partial
    sequences (events aged out of the ring, request still running)
    yield a breakdown of what is known, flagged
    ``"complete": False``."""
    if not evs:
        return None
    out = {k: 0.0 for k in
           ("queue_wait_ms", "prefill_ms", "decode_ms", "preempted_ms")}
    state = None
    t_last = evs[0].t_ns
    t_submit = None
    t_first_tok = None
    seen_first = False
    preempts = 0
    spec_accepted = 0
    spec_drafted = 0
    finish: Optional[Event] = None

    def charge(t_ns: int) -> None:
        nonlocal t_last
        bucket = _STATE_BUCKET.get(state)
        if bucket is not None:
            out[bucket] += (t_ns - t_last) / 1e6
        t_last = t_ns

    for ev in evs:
        k = ev.kind
        if k == "submit":
            state = "queued"
            t_last = ev.t_ns
            t_submit = ev.t_ns
        elif k == "admit":
            charge(ev.t_ns)
            # a re-admission after preemption re-prefills the generated
            # prefix before decode resumes — still preemption cost
            state = "re_prefill" if seen_first else "prefill"
        elif k == "chunk":
            if state == "re_prefill":
                charge(ev.t_ns)
                if ev.attrs.get("final"):
                    state = "decode"
        elif k == "first_token":
            charge(ev.t_ns)
            state = "decode"
            seen_first = True
            if t_first_tok is None:
                t_first_tok = ev.t_ns
        elif k == "accept":
            spec_accepted += int(ev.attrs.get("accepted") or 0)
            spec_drafted += int(ev.attrs.get("drafted") or 0)
        elif k == "preempt":
            charge(ev.t_ns)
            state = "requeued"
            preempts += 1
        elif k == "finish":
            charge(ev.t_ns)
            state = None
            finish = ev
    rid = evs[0].rid
    # complete means the WHOLE lifecycle was observed: a head-truncated
    # sequence (submit aged out of the ring, finish still in it) is
    # missing entire buckets and must not be trusted as a full breakdown
    result = {"rid": rid, **{k: round(v, 3) for k, v in out.items()},
              "preempts": preempts,
              "complete": finish is not None and t_submit is not None}
    if spec_drafted:
        result["spec_accepted"] = spec_accepted
        result["spec_drafted"] = spec_drafted
    if t_submit is not None and t_first_tok is not None:
        result["ttft_ms"] = round((t_first_tok - t_submit) / 1e6, 3)
    if t_submit is not None and finish is not None:
        result["total_ms"] = round((finish.t_ns - t_submit) / 1e6, 3)
    if finish is not None:
        for key in ("tokens", "tpot_ms", "reason"):
            if key in finish.attrs and finish.attrs[key] is not None:
                result[key] = finish.attrs[key]
        # engine-stamped TTFT backfills a ring whose first_token event
        # already aged out (computed-from-events wins when both exist)
        if "ttft_ms" not in result and \
                finish.attrs.get("ttft_ms") is not None:
            result["ttft_ms"] = finish.attrs["ttft_ms"]
    return result


def latency_breakdown(rid: int,
                      event_log: Optional[EventLog] = None
                      ) -> Optional[dict]:
    return breakdown_from_events(timeline(rid, event_log))


def latency_table(since_seq: int = 0,
                  event_log: Optional[EventLog] = None) -> List[dict]:
    """One breakdown row per request observed since ``since_seq``,
    grouped by (engine id, rid) so co-resident engines don't alias.
    Sorted by rid — the per-request latency table serve_bench embeds."""
    lg = event_log or _log
    groups: Dict[tuple, List[Event]] = {}
    for ev in lg.events(since_seq=since_seq):
        if ev.rid is None:
            continue
        groups.setdefault((ev.attrs.get("eng"), ev.rid), []).append(ev)
    ordered = sorted(groups.items(),
                     key=lambda kv: (kv[0][1], str(kv[0][0])))
    rows = []
    for (eng_id, _rid), evs in ordered:
        r = breakdown_from_events(evs)
        if r is not None:
            # co-resident engines reuse rids — the row must say WHICH
            # engine it belongs to, or the advertised (eng, rid) split
            # is impossible for consumers
            r["eng"] = eng_id
            rows.append(r)
    return rows


def _percentiles(vals: List[float]) -> dict:
    from .metrics import percentile as _pctl

    if not vals:
        return {}
    s = sorted(vals)
    n = len(s)

    def pick(q):
        return round(_pctl(s, q), 3)

    return {"p50": pick(50), "p90": pick(90), "p95": pick(95),
            "p99": pick(99), "mean": round(sum(s) / n, 3), "count": n}


def request_latency_stats(window_s: Optional[float] = None,
                          event_log: Optional[EventLog] = None,
                          now_ns: Optional[int] = None,
                          since_seq: int = 0) -> dict:
    """Rolling-window TTFT/TPOT percentiles over finished requests:
    p50/p90/p95/p99 (+mean/count) of the ``ttft_ms``/``tpot_ms`` attrs
    the engine stamps on ``finish`` events. ``window_s=None`` covers
    everything still in the ring."""
    lg = event_log or _log
    fins = lg.events(kind="finish", since_seq=since_seq)
    if window_s is not None:
        now = now_ns if now_ns is not None else time.perf_counter_ns()
        cutoff = now - int(window_s * 1e9)
        fins = [e for e in fins if e.t_ns >= cutoff]
    ttfts = [e.attrs["ttft_ms"] for e in fins
             if e.attrs.get("ttft_ms") is not None]
    tpots = [e.attrs["tpot_ms"] for e in fins
             if e.attrs.get("tpot_ms") is not None]
    return {"window_s": window_s, "requests": len(fins),
            "ttft_ms": _percentiles(ttfts), "tpot_ms": _percentiles(tpots)}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Post-mortem capture: the last ``tail_events`` events, the full
    metrics snapshot, numeric metric DELTAS since the last ``mark()``
    (what moved in the window before the incident — a stuck counter is
    as diagnostic as a spiking one), and the profiler's open spans.
    ``dump()`` returns the document and best-effort writes it as JSON;
    it never raises — a failed file write is flagged with a
    ``"write_error"`` key in the returned document instead."""

    def __init__(self, tail_events: int = 2048):
        self.tail_events = int(tail_events)
        self._lock = threading.Lock()
        self._baseline: Dict[str, float] = {}
        self._baseline_t_ns: Optional[int] = None

    @staticmethod
    def _numeric_view(snapshot: dict) -> Dict[str, float]:
        out = {}
        for name, s in snapshot.items():
            if s.get("type") == "histogram":
                out[name] = float(s.get("count", 0))
            elif s.get("value") is not None:
                out[name] = float(s["value"])
        return out

    def mark(self) -> None:
        """Set the delta baseline (call at steady-state points — the
        sink's flush loop does, so deltas read 'since last flush')."""
        from .metrics import registry

        with self._lock:
            self._baseline = self._numeric_view(registry().snapshot())
            self._baseline_t_ns = time.perf_counter_ns()

    def dump(self, path: Optional[str] = None,
             reason: str = "") -> dict:
        from . import trace as _trace
        from .metrics import registry

        try:
            snap = registry().snapshot()
            cur = self._numeric_view(snap)
            with self._lock:
                base = dict(self._baseline)
                base_t = self._baseline_t_ns
            deltas = {k: round(v - base.get(k, 0.0), 6)
                      for k, v in cur.items()
                      if v != base.get(k, 0.0)}
            # mesh-ordering tags (ISSUE 14): dumps from different
            # ranks of a disaggregated mesh must be orderable — the
            # writer's rank, its agreed clock offset (± uncertainty)
            # and the last consensus epoch it adopted per family say
            # WHERE and WHEN this post-mortem sits in mesh history
            from . import disttrace as _disttrace
            from .sink import _detect_rank

            try:
                from ..distributed.consensus import adopted_epochs
                epochs = adopted_epochs()
            except Exception:  # pragma: no cover - import cycle guard
                epochs = {}
            doc = {
                "kind": "flight_recorder_dump",
                "reason": reason,
                "unix_time": time.time(),
                "t_ns": time.perf_counter_ns(),
                "baseline_t_ns": base_t,
                "rank": _detect_rank(),
                "clock": _disttrace.clock_state(),
                "consensus_epochs": epochs,
                "events": [e.to_dict() for e in _log.tail(self.tail_events)],
                "events_dropped": _log.dropped,
                "metrics": snap,
                "metric_deltas_since_mark": deltas,
                "open_spans": {str(t): s
                               for t, s in _trace.live_spans().items()},
                "scope_summary": _trace.scope_summary(),
            }
            # the last parsed device-trace window (ISSUE 11): a hang
            # or rollback dump carries the newest measured device
            # timeline alongside the host-side evidence (None before
            # any capture; lazy import — post-mortem paths must not
            # pull jax state in)
            try:
                from . import device_trace as _dtrace

                doc["trace_summary"] = _dtrace.last_summary()
            except Exception:
                doc["trace_summary"] = None
        except Exception as e:  # pragma: no cover - post-mortem shield
            doc = {"kind": "flight_recorder_dump", "reason": reason,
                   "error": f"{type(e).__name__}: {e}"}
        if path:
            try:
                with open(path, "w") as f:
                    json.dump(doc, f)
            except OSError as e:
                # a dump must never take the job down, but callers must
                # not advertise a file that does not exist (dump_flight
                # turns this into its documented None)
                doc["write_error"] = f"{type(e).__name__}: {e}"
        return doc


_flight = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _flight


def dump_flight(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Write a flight-recorder dump and return its path. ``path=None``
    falls back to the active sink's directory
    (``flight-<seq>-<sanitized reason>.json``); with neither, nothing
    is written and None returns. A failed file write also returns None
    (the document was lost — don't point post-mortem tooling at a path
    that does not exist). Never raises — this runs inside watchdog
    fires and rollback paths."""
    try:
        if path is None:
            from . import sink as _sink

            s = _sink.active_sink()
            if s is None:
                return None
            tag = "".join(c if c.isalnum() else "-" for c in reason)[:48]
            path = f"{s.directory}/flight-{_log.next_seq}-{tag}.json"
        doc = _flight.dump(path, reason=reason)
        return None if "write_error" in doc else path
    except Exception:  # pragma: no cover - post-mortem shield
        return None
