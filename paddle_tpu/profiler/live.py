"""Live mesh telemetry plane (ISSUE 16): tail per-rank frames, merge,
alert, publish.

The PR 14 trace merger is offline by design — it reads artifacts a run
left behind. This module is the IN-FLIGHT view: every rank's
``MetricsSink`` flush publishes an atomic *telemetry frame*
(``frames/rank<K>-<seq>.json`` — counter values + deltas, last-value
gauges, CUMULATIVE sketch buckets, this flush's clock anchor, adopted
consensus epochs), and a :class:`LiveAggregator` — driver-side or on
any rank, pure stdlib, NO jax and NO collectives — tails those frames
and rewrites two artifacts per tick, atomically:

- ``mesh_status.json`` — machine-readable mesh state: per-rank health
  (frame age, torn count, clock sync, lease corroboration, dead flag),
  mesh-wide latency percentiles from bucket-wise-merged sketches
  (EXACT merge — the mesh p95 is the union sketch's p95, within the
  sketch's stated ``rel_err`` of the true stream), window rollups
  (tokens/s, prefix-hit rate, page pressure, goodput-busy frac), and
  the alert board;
- ``mesh_status.prom`` — the same, Prometheus-textfile-shaped;
- ``mesh_status_history.jsonl`` — a rolling JSONL tail of every
  published status (ISSUE 17), for ``live_dash.py --history``.

Elastic mesh (ISSUE 17): when the consensus board carries a
``member`` family, the aggregator's world FOLLOWS the latest agreed
member set (``membership`` block in the status: epoch, members,
source) instead of the static ``--world`` — a joiner is expected the
moment its membership round publishes, and a voted-out rank stops
reading as "missing". Alert rules may be ``per_rank=True``: the
``dead_rank`` stock rule keeps an independent damped streak per rank,
and its transitions/ring events name the rank.

Transport is the shared directory, like the consensus board — compiled
cross-process collectives are unavailable on this backend, and a file
tail means the aggregator can NEVER block serving: publication is
fire-and-forget on the sink side, and a dead aggregator just leaves
``mesh_status.json`` stale (its own ``ts`` says so).

Honest degradation, per house style:

- a torn/partial frame (killed mid-write before the atomic rename, or
  a corrupted landing) is COUNTED (``torn`` per rank, ``frames_torn``
  mesh-wide) and skipped — never guessed into the merge;
- a rank whose clock never synced aggregates with ``unc=None`` — its
  samples still count (they are real observations), the status just
  cannot bound the cross-host component;
- rank death needs TWO signals: frame age past ``staleness_s`` AND the
  consensus lease stale/absent (when a board is given). Fresh lease +
  stale frames is reported ``stale`` but not ``dead`` — a wedged sink
  on a live rank is a different incident than a dead process.

TTFT source: ranks publishing ``serving/e2e_ttft_ms`` (the disagg
coordinator's offset-corrected end-to-end sketch) win over the plain
engine's ``serving/ttft_ms``, which is bogus-local for imported
requests — if ANY rank has the e2e sketch, only e2e sketches merge.

Alerting: declarative :class:`AlertRule`\\ s evaluated every tick — a
rule fires after ``for_ticks`` consecutive breaches and resolves only
below ``hysteresis * threshold`` (damped: a value oscillating on the
line does not flap). Every transition lands as an ``alert`` event in
the ring AND a sink flush (reason ``alert`` — the transition is on
disk even if the process dies next tick), and the FIRST firing of each
rule dumps the flight recorder: an SLO breach leaves the same forensic
trail a watchdog fire does.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from . import events as _events
from .sketch import DEFAULT_REL_ERR, QuantileSketch

__all__ = ["AlertRule", "LiveAggregator", "default_rules"]

_FRAME_RE = re.compile(r"^rank(\d+)-(\d+)\.json$")

#: status latency key -> frame sketch name (first present wins; see
#: module docstring for the e2e-over-local TTFT rule)
_LATENCY_SOURCES = (
    ("ttft_ms", ("serving/e2e_ttft_ms", "serving/ttft_ms")),
    ("tpot_ms", ("serving/tpot_ms",)),
    ("queue_wait_ms", ("serving/prefill_queue_wait_ms",)),
)


class AlertRule:
    """One declarative health condition over the mesh status.

    ``probe(status) -> Optional[float]`` extracts the watched value
    (None = not evaluable this tick — streaks HOLD, they neither grow
    nor clear on missing data). Breach is ``value >= threshold``
    (probes are phrased so bigger is worse); the rule fires after
    ``for_ticks`` CONSECUTIVE breaches and resolves after
    ``clear_ticks`` consecutive ticks with ``value <
    hysteresis * threshold`` (``hysteresis <= 1`` pulls the resolve
    line below the fire line, so a value sitting on the threshold
    cannot flap the alert).

    ``per_rank=True`` (ISSUE 17 satellite) changes the probe contract:
    ``probe(status) -> Dict[rank, Optional[float]]`` and the rule runs
    an INDEPENDENT streak machine per rank — rank 3 flapping must not
    reset rank 1's breach streak, and one transition names the rank it
    happened on (``"rank"`` in the transition dict and on the ring
    event). The aggregate view stays intact: ``firing`` is true while
    ANY rank fires, ``last_value`` is the worst evaluable rank, and
    ``state()`` keeps the scalar keys plus a ``per_rank`` sub-block.
    A rank that disappears from the probe's dict (left the mesh)
    holds its streaks, like a None value."""

    __slots__ = ("name", "probe", "threshold", "for_ticks",
                 "hysteresis", "clear_ticks", "firing", "fired_count",
                 "last_value", "_streak", "_clear", "per_rank",
                 "_rank_states")

    def __init__(self, name: str,
                 probe: Callable[[dict], Optional[float]],
                 threshold: float, for_ticks: int = 1,
                 hysteresis: float = 1.0, clear_ticks: int = 1,
                 per_rank: bool = False):
        if for_ticks < 1 or clear_ticks < 1:
            raise ValueError("for_ticks/clear_ticks must be >= 1")
        if not 0.0 < hysteresis <= 1.0:
            raise ValueError("hysteresis must be in (0, 1]")
        self.name = name
        self.probe = probe
        self.threshold = float(threshold)
        self.for_ticks = int(for_ticks)
        self.hysteresis = float(hysteresis)
        self.clear_ticks = int(clear_ticks)
        self.per_rank = bool(per_rank)
        self.firing = False
        self.fired_count = 0
        self.last_value: Optional[float] = None
        self._streak = 0
        self._clear = 0
        self._rank_states: Dict[str, dict] = {}

    def evaluate(self, status: dict) -> Optional[str]:
        """Advance the state machine one tick; returns the transition
        (``"firing"`` / ``"resolved"``) or None. Never raises — a
        probe error reads as not-evaluable. Scalar rules only; a
        per-rank rule is driven via :meth:`evaluate_all`."""
        if self.per_rank:
            raise TypeError(f"rule {self.name!r} is per_rank: drive "
                            "it with evaluate_all()")
        try:
            v = self.probe(status)
        except Exception:
            v = None
        self.last_value = None if v is None else float(v)
        if v is None:
            return None
        if not self.firing:
            if v >= self.threshold:
                self._streak += 1
                if self._streak >= self.for_ticks:
                    self.firing = True
                    self.fired_count += 1
                    self._clear = 0
                    return "firing"
            else:
                self._streak = 0
            return None
        if v < self.hysteresis * self.threshold:
            self._clear += 1
            if self._clear >= self.clear_ticks:
                self.firing = False
                self._streak = 0
                return "resolved"
        else:
            self._clear = 0
        return None

    def _step_rank(self, rs: dict, v: Optional[float]) -> Optional[str]:
        """One rank's streak machine — the same damped transitions as
        the scalar path, over the rank's own state dict."""
        rs["value"] = None if v is None else float(v)
        if v is None:
            return None
        if not rs["firing"]:
            if v >= self.threshold:
                rs["streak"] += 1
                if rs["streak"] >= self.for_ticks:
                    rs["firing"] = True
                    rs["fired_count"] += 1
                    rs["clear"] = 0
                    return "firing"
            else:
                rs["streak"] = 0
            return None
        if v < self.hysteresis * self.threshold:
            rs["clear"] += 1
            if rs["clear"] >= self.clear_ticks:
                rs["firing"] = False
                rs["streak"] = 0
                return "resolved"
        else:
            rs["clear"] = 0
        return None

    def evaluate_all(self, status: dict) -> List[dict]:
        """Advance one tick and return this rule's transition dicts
        (possibly several for a per-rank rule: rank 1 can fire on the
        same tick rank 3 resolves)."""
        if not self.per_rank:
            tr = self.evaluate(status)
            if tr is None:
                return []
            return [{"rule": self.name, "state": tr,
                     "value": self.last_value,
                     "threshold": self.threshold,
                     "fired_count": self.fired_count}]
        try:
            vals = self.probe(status)
        except Exception:
            vals = None
        if not isinstance(vals, dict):
            vals = {}
        out = []
        for rank in sorted(vals, key=str):
            rs = self._rank_states.setdefault(
                str(rank), {"firing": False, "streak": 0, "clear": 0,
                            "fired_count": 0, "value": None})
            tr = self._step_rank(rs, vals[rank])
            if tr == "firing":
                self.fired_count += 1
            if tr is not None:
                out.append({"rule": self.name, "state": tr,
                            "rank": str(rank), "value": rs["value"],
                            "threshold": self.threshold,
                            "fired_count": self.fired_count})
        self.firing = any(rs["firing"]
                          for rs in self._rank_states.values())
        evaluable = [rs["value"] for rs in self._rank_states.values()
                     if rs["value"] is not None]
        self.last_value = max(evaluable) if evaluable else None
        return out

    def state(self) -> dict:
        st = {"firing": self.firing, "value": self.last_value,
              "threshold": self.threshold,
              "fired_count": self.fired_count}
        if self.per_rank:
            st["per_rank"] = {
                r: {"firing": rs["firing"], "value": rs["value"],
                    "fired_count": rs["fired_count"]}
                for r, rs in sorted(self._rank_states.items())}
        return st


def default_rules(ttft_p95_ms: float = 2000.0,
                  pool_util: float = 0.98,
                  for_ticks: int = 3) -> List[AlertRule]:
    """The stock rule set the ISSUE names. ``ttft_p95_ms`` is the SLO
    target; ``for_ticks`` damps the sustained-condition rules (W
    consecutive windows). ``dead_rank`` and ``events_lost`` fire on
    the first breach — neither is a transient."""

    def _p95(st, key="ttft_ms"):
        m = st["latency"].get(key)
        return None if m is None else m.get("p95")

    def _dead(st):
        return {r: float(blk["dead"])
                for r, blk in st["ranks"].items()}

    def _stall(st):
        tps = st["rollups"].get("tokens_per_sec")
        if tps is None:             # no window yet — not evaluable
            return None
        active = max((r.get("gauges", {}).get("serving/active_slots")
                      or 0.0) for r in st["ranks"].values()) \
            if st["ranks"] else 0.0
        return 1.0 if tps == 0.0 and active > 0.0 else 0.0

    def _pressure(st):
        return st["rollups"].get("page_pressure")

    def _lost(st):
        return float(st["events_lost"])

    return [
        AlertRule("p95_ttft_over_target", _p95, ttft_p95_ms,
                  for_ticks=for_ticks, hysteresis=0.9),
        AlertRule("dead_rank", _dead, 1.0, per_rank=True),
        AlertRule("decode_stall", _stall, 1.0, for_ticks=for_ticks),
        AlertRule("pool_pressure", _pressure, pool_util,
                  for_ticks=for_ticks, hysteresis=0.95),
        AlertRule("events_lost", _lost, 1.0),
    ]


class _RankState:
    __slots__ = ("last_seq", "frames", "torn", "ts", "t_ref",
                 "clock", "counters", "gauges", "sketches",
                 "events_lost", "adopted_epochs")

    def __init__(self):
        self.last_seq = -1
        self.frames = 0
        self.torn = 0
        self.ts: Optional[float] = None
        self.t_ref: Optional[float] = None
        self.clock: dict = {}
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, Optional[float]] = {}
        self.sketches: Dict[str, QuantileSketch] = {}
        self.events_lost = 0
        self.adopted_epochs: Dict[str, int] = {}


class LiveAggregator:
    """See module docstring. ``tick()`` is one scan-merge-publish
    pass; ``start()``/``stop()`` wrap it in a daemon thread for
    embedding (serve_bench ``--live-status``); ``run()`` drives it in
    the foreground (tools/live_dash.py). Holds no jax state, issues no
    collectives — pure host I/O, safe anywhere."""

    def __init__(self, root: str, interval_s: float = 2.0,
                 staleness_s: Optional[float] = None,
                 world: Optional[int] = None,
                 board_dir: Optional[str] = None,
                 lease_s: float = 5.0,
                 rules: Optional[List[AlertRule]] = None,
                 prefix: str = "paddle_tpu",
                 emit_alerts: bool = True,
                 history_limit: int = 512):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.root = root
        self.interval_s = float(interval_s)
        #: a rank is STALE once its newest frame is older than this;
        #: default 3 aggregation ticks — callers whose sinks flush
        #: slower than that must pass ~1.5x the sink interval
        self.staleness_s = (3.0 * self.interval_s
                            if staleness_s is None
                            else float(staleness_s))
        self.world = world
        self.board_dir = board_dir
        self.lease_s = float(lease_s)
        self.rules = default_rules() if rules is None else list(rules)
        self.prefix = prefix
        self.emit_alerts = bool(emit_alerts)
        self.status_json = os.path.join(root, "mesh_status.json")
        self.status_prom = os.path.join(root, "mesh_status.prom")
        #: rolling JSONL of every published status (ISSUE 17
        #: satellite) — ``live_dash.py --history`` replays it; kept to
        #: the last ``history_limit`` lines (0 disables the history)
        self.status_history = os.path.join(
            root, "mesh_status_history.jsonl")
        self.history_limit = int(history_limit)
        self._history_appends = 0
        self._ranks: Dict[int, _RankState] = {}
        self._ticks = 0
        self._last_status: Optional[dict] = None
        self._prev_now: Optional[float] = None
        self._prev_counter_sums: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- frame ingestion ---------------------------------------------------
    def _frames_dirs(self) -> List[str]:
        """``<root>/frames`` (single-process sink) plus every
        ``<root>/rank<K>/frames`` (per-rank subdir mesh layout)."""
        out = []
        d = os.path.join(self.root, "frames")
        if os.path.isdir(d):
            out.append(d)
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in sorted(names):
            if n.startswith("rank"):
                d = os.path.join(self.root, n, "frames")
                if os.path.isdir(d):
                    out.append(d)
        return out

    def _ingest(self, st: _RankState, frame: dict) -> None:
        st.ts = float(frame["ts"])
        clock = frame.get("clock") or {}
        st.clock = clock
        # placement on the reference clock (PR 14 sign convention:
        # w_ref = w_k - offset_s); an unsynced rank places on its own
        # wall — same-host it coincides, cross-host the status's
        # synced=False says the age is unbounded-skew
        if clock.get("synced") and clock.get("offset_s") is not None:
            st.t_ref = float(clock["wall_s"]) - float(clock["offset_s"])
        else:
            st.t_ref = st.ts
        st.counters = {n: float(c["v"])
                       for n, c in (frame.get("counters") or {}).items()}
        st.gauges = dict(frame.get("gauges") or {})
        st.events_lost += int(frame.get("events_lost") or 0)
        st.adopted_epochs = dict(frame.get("adopted_epochs") or {})
        sketches = {}
        for name, d in (frame.get("sketches") or {}).items():
            # a malformed sketch is a torn frame in sheep's clothing —
            # from_dict raises, the caller counts
            sketches[name] = QuantileSketch.from_dict(d)
        st.sketches = sketches

    def _scan(self) -> None:
        """Pick up every frame newer than each rank's cursor, in seq
        order. A frame that fails to parse/validate advances the
        cursor (the rename was atomic — a bad landing is FINAL) and
        bumps the rank's ``torn`` count; the rank's state keeps the
        last good frame."""
        pending: Dict[int, List] = {}
        for d in self._frames_dirs():
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                m = _FRAME_RE.match(n)
                if not m:
                    continue
                r, seq = int(m.group(1)), int(m.group(2))
                st = self._ranks.get(r)
                if st is not None and seq <= st.last_seq:
                    continue
                pending.setdefault(r, []).append(
                    (seq, os.path.join(d, n)))
        for r, files in pending.items():
            st = self._ranks.setdefault(r, _RankState())
            for seq, path in sorted(files):
                if seq <= st.last_seq:
                    continue
                st.last_seq = seq
                try:
                    with open(path) as f:
                        frame = json.load(f)
                    if frame.get("kind") != "telemetry_frame" or \
                            int(frame.get("rank", -1)) != r:
                        raise ValueError("frame header mismatch")
                    self._ingest(st, frame)
                    st.frames += 1
                except (OSError, ValueError, KeyError, TypeError):
                    st.torn += 1

    # -- aggregation -------------------------------------------------------
    def _merged_sketches(self) -> Dict[str, dict]:
        """Mesh-wide latency block: per status key, the bucket-wise
        merge of every rank's cumulative sketch for the chosen source
        metric."""
        out: Dict[str, dict] = {}
        any_e2e = any("serving/e2e_ttft_ms" in st.sketches
                      for st in self._ranks.values())
        for key, sources in _LATENCY_SOURCES:
            if key == "ttft_ms" and any_e2e:
                sources = ("serving/e2e_ttft_ms",)
            merged: Optional[QuantileSketch] = None
            contributing: List[int] = []
            for r, st in self._ranks.items():
                for name in sources:
                    sk = st.sketches.get(name)
                    if sk is not None and sk.count:
                        merged = sk.copy() if merged is None \
                            else merged.merge(sk)
                        contributing.append(r)
                        break
            if merged is None or not merged.count:
                continue
            # clock-uncertainty bound on the CROSS-HOST component:
            # only TTFT has one (it spans submit and first-token hosts
            # — worst pair = 2x the largest per-rank bound); TPOT and
            # queue-wait are single-monotonic-clock durations. Any
            # contributing unsynced rank makes the bound unstatable.
            if key != "ttft_ms":
                unc_ms: Optional[float] = 0.0
            else:
                uncs = []
                for r in contributing:
                    c = self._ranks[r].clock
                    if not c.get("synced") or c.get("unc_s") is None:
                        uncs = None
                        break
                    uncs.append(float(c["unc_s"]))
                unc_ms = None if uncs is None \
                    else round(2.0 * max(uncs) * 1e3, 6)
            out[key] = {
                "count": merged.count,
                "min": merged.min, "max": merged.max,
                "p50": merged.percentile(50),
                "p90": merged.percentile(90),
                "p95": merged.percentile(95),
                "p99": merged.percentile(99),
                "unc_ms": unc_ms, "rel_err": merged.rel_err,
                "ranks": sorted(contributing),
            }
        return out

    def _counter_sum(self, name: str) -> float:
        return sum(st.counters.get(name, 0.0)
                   for st in self._ranks.values())

    def _gauge_max(self, name: str) -> Optional[float]:
        vals = [st.gauges.get(name) for st in self._ranks.values()]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    def _rollups(self, now: float) -> dict:
        """Window rollups from counter deltas between aggregation
        ticks (rate keys are None on the first tick — no window yet)."""
        dt = None if self._prev_now is None else max(
            now - self._prev_now, 1e-9)
        sums = {n: self._counter_sum(n) for n in
                ("serving/tokens_generated", "serving/prefix_hit_tokens",
                 "serving/prompt_tokens")}
        tps = None
        if dt is not None:
            d = sums["serving/tokens_generated"] - \
                self._prev_counter_sums.get(
                    "serving/tokens_generated", 0.0)
            tps = round(max(d, 0.0) / dt, 3)
        hit_rate = None
        if sums["serving/prompt_tokens"] > 0:
            hit_rate = round(sums["serving/prefix_hit_tokens"]
                             / sums["serving/prompt_tokens"], 6)
        self._prev_counter_sums = sums
        return {
            "tokens_per_sec": tps,
            "prefix_hit_rate": hit_rate,
            "page_pressure": self._gauge_max("serving/page_util"),
            "goodput_busy_frac":
                self._gauge_max("trace/goodput_busy_frac"),
        }

    def _membership(self) -> Optional[dict]:
        """The latest agreed ``member`` decision on the consensus
        board (ISSUE 17): the aggregator's world FOLLOWS the mesh's
        own membership — a joiner shows up in ``mesh_status`` the
        moment the round publishes, a dead rank stops counting as
        "missing" once voted out. None when no board, no member
        family, or no decision yet (static-world fallback)."""
        if self.board_dir is None:
            return None
        fam = os.path.join(self.board_dir, "member")
        try:
            # dir names are e<epoch>, zero-padded by the consensus
            # (e000004) — keep the real name, sort by the number
            epochs = sorted((int(n[1:]), n) for n in os.listdir(fam)
                            if n.startswith("e") and n[1:].isdigit())
        except OSError:
            return None
        for e, name in reversed(epochs):
            path = os.path.join(fam, name, "decision.json")
            try:
                with open(path) as f:
                    dec = json.load(f)
                members = {str(k): str(v) for k, v in
                           ((dec.get("value") or {})
                            .get("members") or {}).items()}
                if not members:
                    raise ValueError("empty member table")
                return {"epoch": e, "members": members,
                        "source": "board"}
            except (OSError, ValueError, KeyError, TypeError):
                continue            # undecided/torn epoch: look older
        return None

    # -- publication -------------------------------------------------------
    def _rank_block(self, now: float,
                    world: Optional[int]) -> Dict[str, dict]:
        lease_ages: Dict[int, float] = {}
        if self.board_dir is not None:
            try:
                from ..distributed.consensus import lease_ages as _la
                lease_ages = _la(self.board_dir, world)
            except Exception:
                lease_ages = {}
        out: Dict[str, dict] = {}
        for r, st in sorted(self._ranks.items()):
            age = None if st.t_ref is None else max(0.0, now - st.t_ref)
            stale = age is not None and age >= self.staleness_s
            lease_age = lease_ages.get(r)
            # death needs corroboration when a board is present: stale
            # frames AND a stale/absent lease. Without a board, frame
            # staleness alone decides (documented weaker evidence).
            dead = stale and (self.board_dir is None
                              or lease_age is None
                              or lease_age >= self.lease_s)
            out[str(r)] = {
                "seq": st.last_seq, "frames": st.frames,
                "torn": st.torn,
                "age_s": None if age is None else round(age, 3),
                "synced": bool(st.clock.get("synced")),
                "offset_s": st.clock.get("offset_s"),
                "unc_s": st.clock.get("unc_s"),
                "stale": stale, "dead": dead,
                "lease_age_s": None if lease_age is None
                else round(lease_age, 3),
                "events_lost": st.events_lost,
                "gauges": st.gauges,
                "adopted_epochs": st.adopted_epochs,
            }
        return out

    def _build_status(self, now: float) -> dict:
        membership = self._membership()
        # the agreed member set outranks the static --world: joiners
        # count, voted-out ranks stop reading as "missing"
        world = (len(membership["members"]) if membership
                 else self.world)
        ranks = self._rank_block(now, world)
        if membership:
            present = {str(r) for r in membership["members"]}
            missing = bool(present - set(ranks))
        else:
            missing = world is not None and len(ranks) < world
        status = {
            "kind": "mesh_status", "ts": round(now, 6),
            "root": self.root, "tick": self._ticks,
            "interval_s": self.interval_s,
            "staleness_s": self.staleness_s,
            "world": world,
            "membership": membership,
            "ranks": ranks,
            "partial": bool(missing
                            or any(r["dead"] or r["torn"]
                                   for r in ranks.values())),
            "frames_torn": sum(r["torn"] for r in ranks.values()),
            "events_lost": sum(r["events_lost"]
                               for r in ranks.values()),
            "latency": self._merged_sketches(),
            "rollups": self._rollups(now),
        }
        return status

    def _prom_text(self, status: dict) -> str:
        p = self.prefix
        lines = [f"# TYPE {p}_mesh_partial gauge",
                 f"{p}_mesh_partial {int(status['partial'])}",
                 f"# TYPE {p}_mesh_frames_torn gauge",
                 f"{p}_mesh_frames_torn {status['frames_torn']}",
                 f"# TYPE {p}_mesh_events_lost gauge",
                 f"{p}_mesh_events_lost {status['events_lost']}"]
        if status.get("membership"):
            lines += [f"# TYPE {p}_mesh_members gauge",
                      f"{p}_mesh_members "
                      f"{len(status['membership']['members'])}"]
        for r, blk in status["ranks"].items():
            lines.append(f'{p}_mesh_rank_dead{{rank="{r}"}} '
                         f'{int(blk["dead"])}')
        for key, m in status["latency"].items():
            n = f"{p}_mesh_{key.replace('/', '_')}"
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_count {m['count']}")
            for q, k in ((0.5, "p50"), (0.9, "p90"),
                         (0.95, "p95"), (0.99, "p99")):
                lines.append(f'{n}{{quantile="{q}"}} {m[k]}')
        for key, v in status["rollups"].items():
            if v is None:
                continue
            n = f"{p}_mesh_{key}"
            lines += [f"# TYPE {n} gauge", f"{n} {v}"]
        for rule in self.rules:
            lines.append(f'{p}_mesh_alert_firing{{rule="{rule.name}"}}'
                         f' {int(rule.firing)}')
        return "\n".join(lines) + "\n"

    def _publish(self, status: dict) -> None:
        os.makedirs(self.root, exist_ok=True)
        for path, text in ((self.status_json,
                            json.dumps(status, indent=1)),
                           (self.status_prom,
                            self._prom_text(status))):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        self._append_history(status)

    def _append_history(self, status: dict) -> None:
        """One compact JSONL line per publish, rolled to the last
        ``history_limit`` lines. The trim rewrites atomically (tmp +
        replace) so a tailing dashboard never reads a torn file; it
        runs every 64 appends, so the file briefly overshoots the
        limit — a bounded-disk guarantee, not an exact-length one."""
        if self.history_limit <= 0:
            return
        line = json.dumps(status, separators=(",", ":"))
        with open(self.status_history, "a") as f:
            f.write(line + "\n")
        self._history_appends += 1
        if self._history_appends % 64 == 0:
            try:
                with open(self.status_history) as f:
                    lines = f.readlines()
                if len(lines) > self.history_limit:
                    tmp = self.status_history + ".tmp"
                    with open(tmp, "w") as f:
                        f.writelines(lines[-self.history_limit:])
                    os.replace(tmp, self.status_history)
            except OSError:
                pass                # next trim pass retries

    # -- alerting ----------------------------------------------------------
    def _evaluate_rules(self, status: dict) -> List[dict]:
        transitions = []
        for rule in self.rules:
            transitions.extend(rule.evaluate_all(status))
        status["alerts"] = {r.name: r.state() for r in self.rules}
        status["alert_transitions"] = transitions
        if transitions and self.emit_alerts:
            self._emit_transitions(transitions)
        return transitions

    def _emit_transitions(self, transitions: List[dict]) -> None:
        """Alert side effects, all shielded — telemetry must never
        take the aggregator down: the ``alert`` ring event, a sink
        flush (reason ``alert`` — the transition is on disk NOW, not
        at the next interval), and a flight dump on each rule's FIRST
        firing."""
        from . import sink as _sink
        for t in transitions:
            try:
                kw = dict(rule=t["rule"], state=t["state"],
                          value=t["value"], threshold=t["threshold"])
                if "rank" in t:       # per-rank rule: name the rank
                    kw["rank"] = t["rank"]
                _events.emit("alert", **kw)
            except Exception:
                pass
            if t["state"] == "firing" and t["fired_count"] == 1:
                try:
                    _events.dump_flight(f"alert-{t['rule']}")
                except Exception:
                    pass
        try:
            _sink.flush_active("alert", timeout=1.0)
        except Exception:
            pass

    # -- driving -----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> dict:
        """One scan-merge-publish-alert pass; returns (and retains)
        the status document it wrote."""
        with self._lock:
            now = time.time() if now is None else float(now)
            self._ticks += 1
            self._scan()
            status = self._build_status(now)
            self._evaluate_rules(status)
            try:
                self._publish(status)
            except OSError:
                # a torn publish target is the CONSUMER's outage, not
                # serving's — keep ticking, the next rewrite heals it
                pass
            self._last_status = status
            self._prev_now = now
            return status

    @property
    def status(self) -> Optional[dict]:
        """The last tick's document (None before the first tick)."""
        return self._last_status

    def start(self) -> "LiveAggregator":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="live-aggregator", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass                # next tick retries; never escapes

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval_s + 5)
        self._thread = None
        if final_tick:
            self.tick()

    def run(self, duration_s: Optional[float] = None,
            on_tick: Optional[Callable[[dict], None]] = None) -> None:
        """Foreground drive (tools/live_dash.py): tick every
        ``interval_s`` until ``duration_s`` elapses (forever if None)
        or KeyboardInterrupt."""
        t0 = time.time()
        while duration_s is None or time.time() - t0 < duration_s:
            st = self.tick()
            if on_tick is not None:
                on_tick(st)
            time.sleep(self.interval_s)

    def __enter__(self) -> "LiveAggregator":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
