"""Recompilation telemetry: make silent jit retraces visible.

A hybrid/pipeline step function is supposed to trace ONCE and then hit
the jit cache forever; every extra trace is minutes of XLA compile time
silently folded into a training run (a changed batch shape, a dtype
drift, a python-scalar argument). The reference framework never had this
failure mode (programs were built ahead of time); a jit-staged framework
needs a watcher.

Mechanism: instrumented step functions call ``mark_trace(site, *trees)``
at the TOP of their traced body. Python side effects run exactly once per
trace, so the call itself is the cache-miss signal — zero per-step cost,
no jax internals. The watcher keeps each site's abstract signature
(shape/dtype of every leaf); any trace after a site's first is a
**retrace** and is recorded with the shapes that triggered it, diffed
against the previous signature.
"""
from __future__ import annotations

import itertools
import logging
import threading
from contextlib import contextmanager
from typing import Any, Dict, List

import jax

from . import trace as _trace
from .metrics import registry

logger = logging.getLogger("paddle_tpu.profiler")

_lock = threading.Lock()
_sites: Dict[str, List[tuple]] = {}       # site -> signature history
_retraces: List[dict] = []
_MAX_HISTORY = 64
_suppress = 0
_site_seq = itertools.count()


def unique_site(prefix: str) -> str:
    """A process-unique site name for per-instance step functions (two
    trainers must not alias one site — the second's FIRST trace would
    read as the first's retrace)."""
    return f"{prefix}#{next(_site_seq)}"


@contextmanager
def suppressed():
    """Traces inside this context update signature history but not the
    public retrace counter/log — for internal diagnostic lowerings
    (aot_lower for collective accounting, memory_analysis) that re-trace
    by design and are not silent recompiles."""
    global _suppress
    _suppress += 1
    try:
        yield
    finally:
        _suppress -= 1


def _aval_sig(x: Any) -> tuple:
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (tuple(aval.shape), str(aval.dtype))
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    return ((), type(x).__name__)


def signature(*trees) -> tuple:
    return tuple(_aval_sig(leaf)
                 for t in trees for leaf in jax.tree_util.tree_leaves(t))


def mark_trace(site: str, *trees) -> None:
    """Record that ``site`` is being traced with these arguments. Call
    from INSIDE the traced function body (first line). Signature history
    is tracked unconditionally (a site first traced while profiling was
    off must still detect its first retrace after enable); the public
    counter/log only move while the profiler is enabled."""
    sig = signature(*trees)
    with _lock:
        hist = _sites.setdefault(site, [])
        is_retrace = bool(hist)
        prev = hist[-1] if hist else None
        hist.append(sig)
        if len(hist) > _MAX_HISTORY:
            del hist[: len(hist) - _MAX_HISTORY]
    if is_retrace and _trace.is_enabled() and not _suppress:
        # zip_longest: a leaf-count change (argument added/removed) must
        # show up as a diff entry, not be truncated to "same signature"
        ev = {"site": site, "trace_no": len(hist),
              "prev_signature": prev, "signature": sig,
              "changed": [
                  {"index": i, "prev": p, "new": n}
                  for i, (p, n) in enumerate(
                      itertools.zip_longest(prev, sig)) if p != n]}
        with _lock:
            _retraces.append(ev)
        registry().counter("profiler/retraces").add(1)
        logger.warning(
            "jit retrace at %s (trace #%d): %s", site, len(hist),
            ev["changed"] if ev["changed"]
            else "same signature (function object rebuilt)")


def watch(fn, site: str = None):  # noqa: RUF013 - mirrors functools style
    """Wrap an arbitrary function so every (re)trace of it is recorded:
    ``step = jax.jit(profiler.watch(step_fn, "my.step"))``."""
    name = site or getattr(fn, "__qualname__", getattr(fn, "__name__",
                                                       "fn"))

    def wrapped(*args, **kwargs):
        mark_trace(name, args, kwargs)
        return fn(*args, **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", "wrapped")
    return wrapped


def retraces() -> List[dict]:
    with _lock:
        return list(_retraces)


def clear_log() -> None:
    """Clear the public retrace log but KEEP signature history — a site
    first traced before this call must still read as a retrace on its
    next re-trace (enable() calls this; reset() drops history too)."""
    with _lock:
        _retraces.clear()


def trace_counts() -> Dict[str, int]:
    with _lock:
        return {site: len(h) for site, h in _sites.items()}


def reset() -> None:
    with _lock:
        _sites.clear()
        _retraces.clear()
