"""Runtime metrics registry: counters, gauges, histograms with rank-aware
aggregation.

Reference analogue: distributed/fleet/metrics/metric.py aggregates ad-hoc
numpy values over the RoleMaker's Gloo ring; here the registry is the
first-class store (steps, tokens, per-phase ms, collective bytes, memory
high-water marks) and cross-rank reduction rides the same eager collective
helpers (distributed/fleet/metrics.py -> distributed/collective.py over
the jax coordination service). world_size == 1 degenerates to identity, so
every aggregation path is exercisable in single-process tests.

Instrumentation sites guard their .add()/.set() calls on
``profiler.is_enabled()`` — the registry itself is always usable directly
(a user metric does not need the tracer to be on).
"""
from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .sketch import QuantileSketch


def percentile(sorted_vals, q: float) -> Optional[float]:
    """Nearest-rank percentile over a PRE-SORTED sequence (None when
    empty). The repo's one quantile convention — Histogram reservoirs,
    event-timeline stats (events._percentiles) and serve_bench all call
    this helper, so a p95 means the same thing in every artifact."""
    n = len(sorted_vals)
    if n == 0:
        return None
    return sorted_vals[min(int(q / 100.0 * n), n - 1)]


class Counter:
    """Monotonic accumulator (tokens seen, steps run, retraces, bytes)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def add(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += float(v)

    @property
    def value(self) -> float:
        return self._v

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._v}


class Gauge:
    """Last-value metric (memory high-water, phase ms, learning rate)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def set_max(self, v: float) -> None:
        """High-water-mark update: keep the max of current and v."""
        with self._lock:
            v = float(v)
            if self._v is None or v > self._v:
                self._v = v

    @property
    def value(self) -> Optional[float]:
        return self._v

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._v}


class Histogram:
    """Distribution metric (per-step ms). Backed by a mergeable
    relative-error quantile sketch (sketch.py, ISSUE 16): exact
    count/sum/min/max, percentiles within a DOCUMENTED 1% relative
    error, bounded size over a million-step run — and cross-rank
    aggregation merges bucket-wise (exact), retiring the PR 9
    NaN-padded bounded-reservoir gather whose error depended on what
    the recency window happened to hold."""

    __slots__ = ("name", "_sk", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._sk = QuantileSketch()
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sk.observe(v)

    @property
    def count(self) -> int:
        return self._sk.count

    def percentile(self, q: float) -> Optional[float]:
        """Within the sketch's ``rel_err`` of the nearest-rank value
        over the FULL stream (no recency window anymore)."""
        with self._lock:
            return self._sk.percentile(q)

    def sketch_dict(self) -> dict:
        """Consistent JSON form of the backing sketch (one lock hold)
        — the telemetry-frame payload and the aggregate() wire form."""
        with self._lock:
            return self._sk.to_dict()

    def snapshot(self) -> dict:
        with self._lock:
            return self._sk.snapshot()


class MetricsRegistry:
    """Named metric store. ``counter/gauge/histogram(name)`` create on
    first use (prometheus-client idiom); ``aggregate()`` reduces across
    ranks; ``snapshot()`` is the JSON-ready export."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def sketch_dicts(self) -> Dict[str, dict]:
        """JSON sketch payload of every NON-EMPTY histogram — the
        telemetry frame's ``sketches`` section (ISSUE 16). Empty ones
        are omitted: a frame is an increment, not a schema census."""
        with self._lock:
            items = [(n, m) for n, m in self._metrics.items()
                     if isinstance(m, Histogram)]
        return {n: d for n, d in ((n, m.sketch_dict()) for n, m in
                                  items) if d["n"]}

    @staticmethod
    def _schema_union(snap: Dict[str, dict]) -> List[Tuple[str, str]]:
        """All ranks' (name, type) pairs, unioned and sorted — the ONE
        deterministic reduction order every rank walks in aggregate().
        Each rank's schema rides an allgather of its JSON encoding,
        padded to the allreduced max length (collectives move fixed-size
        buffers, not strings)."""
        from ..distributed.collective import all_gather
        from ..distributed.env import get_world_size
        from ..distributed.fleet import metrics as fm
        from ..framework.tensor import Tensor

        local = sorted((n, s["type"]) for n, s in snap.items())
        if get_world_size() <= 1:
            return local
        payload = np.frombuffer(
            json.dumps(local).encode(), np.uint8).copy()
        buf = np.zeros(int(fm.max(payload.size)), np.uint8)
        buf[: payload.size] = payload
        gathered: list = []
        all_gather(gathered, Tensor(buf))
        union = set()
        for t in gathered:
            raw = bytes(np.asarray(t._value).astype(np.uint8))
            union.update(tuple(p) for p in json.loads(
                raw.rstrip(b"\x00").decode()))
        return sorted(union)

    def _gather_sketch(self, name: str) -> Optional[QuantileSketch]:
        """All ranks' sketches for histogram ``name`` merged into ONE
        (bucket-wise add — exact; just the local sketch at world_size
        1). Each rank's JSON-encoded sketch rides a zero-padded uint8
        allgather after a max-length allreduce (the _schema_union wire
        idiom); every rank issues the identical collective sequence
        even when it lacks the metric locally — an empty sketch is the
        merge's neutral element. Width 0 (no rank has a sample) skips
        the gather on every rank alike and returns None."""
        from ..distributed.collective import all_gather
        from ..distributed.env import get_world_size
        from ..distributed.fleet import metrics as fm
        from ..framework.tensor import Tensor

        with self._lock:
            m = self._metrics.get(name)
        local = m.sketch_dict() if isinstance(m, Histogram) \
            else QuantileSketch().to_dict()
        if get_world_size() <= 1:
            return QuantileSketch.from_dict(local) if local["n"] \
                else None
        payload = np.frombuffer(
            json.dumps(local).encode(), np.uint8).copy()
        any_n = int(fm.max(1 if local["n"] else 0))
        width = int(fm.max(payload.size))
        if not any_n:
            return None
        buf = np.zeros(width, np.uint8)
        buf[: payload.size] = payload
        gathered: list = []
        all_gather(gathered, Tensor(buf))
        merged = QuantileSketch()
        for t in gathered:
            raw = bytes(np.asarray(t._value).astype(np.uint8))
            merged.merge(QuantileSketch.from_dict(
                json.loads(raw.rstrip(b"\x00").decode())))
        return merged if merged.count else None

    def aggregate(self) -> Dict[str, dict]:
        """Cross-rank reduction of the snapshot: counters and histogram
        count/sum are SUM-reduced, gauges and histogram min/max take the
        MAX/MIN envelope (a fleet-wide high-water mark is the max over
        ranks), and histogram quantiles come from the MERGED rank
        sketches (bucket-wise add — EXACT: the mesh percentile equals
        the one a single union sketch would report, within the sketch's
        stated rel_err of the true stream; ISSUE 16, retiring the
        NaN-padded bounded-reservoir gather whose error was whatever
        the recency window held). Rides distributed/fleet/metrics.py —
        identity at world_size 1.

        Every fm.* call is a collective, so ranks MUST issue the same
        sequence: the schema union above aligns rank-dependent metric
        sets (a retrace counter only rank 0 created, a histogram still
        empty on rank 1) and its sorted order fixes the pairing; a
        locally-missing metric contributes the reduction's neutral
        element instead of skipping the collective."""
        from ..distributed.env import get_world_size
        from ..distributed.fleet import metrics as fm

        snap = self.snapshot()
        if get_world_size() <= 1:
            return snap
        for name, typ in self._schema_union(snap):
            s = snap.get(name)
            if s is None or s["type"] != typ:
                s = snap[name] = (
                    {"type": "histogram", "count": 0}
                    if typ == "histogram" else {"type": typ, "value": None})
            if typ == "counter":
                s["value"] = float(fm.sum(s["value"] or 0.0))
            elif typ == "gauge":
                v = s["value"]
                red = float(fm.max(v if v is not None else -np.inf))
                s["value"] = None if red == -np.inf else red
            elif typ == "histogram":
                have = bool(s.get("count"))
                n = int(fm.sum(s.get("count", 0)))
                tot = float(fm.sum(s.get("sum", 0.0)))
                mn = float(fm.min(s["min"] if have else np.inf))
                mx = float(fm.max(s["max"] if have else -np.inf))
                if n:
                    s.update(count=n, sum=tot, mean=tot / n,
                             min=mn, max=mx)
                merged = self._gather_sketch(name)
                if merged is not None:
                    s.update(p50=merged.percentile(50),
                             p90=merged.percentile(90),
                             p95=merged.percentile(95),
                             p99=merged.percentile(99))
                else:
                    for q in ("p50", "p90", "p95", "p99"):
                        s.pop(q, None)
        return snap


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry
