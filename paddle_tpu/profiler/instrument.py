"""Instrumentation helpers shared by the trainer/pipeline hooks.

- collective accounting: parse a lowered (StableHLO) program for
  cross-device collectives and sum the bytes they move — the number that
  makes an allreduce-compression experiment (EQuARX-style) attributable
  instead of inferred from wall-clock deltas;
- device memory high-water marks via ``Device.memory_stats()`` (absent on
  CPU and behind some remote-device tunnels — callers get None, never an
  exception);
- batch token counting for throughput metrics.
"""
from __future__ import annotations

import re
import time
from typing import Optional

import jax
import numpy as np

from .metrics import registry

# StableHLO collective ops (jax lowers psum/all_gather/ppermute/... to
# these). The text form is `%x = "stablehlo.all_reduce"(...)` or
# `stablehlo.all_reduce(...)` depending on printer version.
_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|all_to_all|collective_permute|"
    r"reduce_scatter|collective_broadcast)")
_TENSOR_RE = re.compile(r"tensor<([0-9x]*)x?([a-z][a-z0-9]+)>")
# everything after the function-type arrow: the op's result type(s)
_ARROW_RE = re.compile(r"->\s*(.*)$")
# post-partitioning HLO spelling (`compiled.as_text()`): the op name is
# dash-separated and the RESULT type(s) sit between `=` and the op name,
# e.g. `%ar = f32[8,4]{1,0} all-reduce(...)` or a `(f32[..], ...)` tuple.
# Async pairs: count the `-done` op (its result is the payload) and skip
# `-start` (its result tuple aliases operand+result — double the bytes).
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\(?[a-z][a-z0-9]+\[[^=]*?)\s"
    r"(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)(?:-done)?\(")
_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]+)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1, "pred": 1,
    # compiled-HLO spellings (`compiled.as_text()` prints s8/u32/...;
    # StableHLO prints i8/ui32/...). Without these an int8 collective's
    # payload (quantized AllReduce, qcomm.py) would fall through to the
    # 4-byte default and be counted as if it were still f32.
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1,
}

#: canonical spelling per dtype family, so byte breakdowns key the same
#: whether parsed from StableHLO (i8) or compiled HLO (s8)
_DTYPE_CANON = {"s8": "i8", "u8": "ui8", "s16": "i16", "u16": "ui16",
                "s32": "i32", "u32": "ui32", "s64": "i64", "u64": "ui64",
                "pred": "i1"}


def _tensor_bytes(dims: str, dtype: str) -> int:
    n = 1
    if dims:
        for d in dims.split("x"):
            if d:
                n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_stats(lowered_text: str) -> dict:
    """Count collectives and the bytes they move in a lowered program.

    ``lowered_text``: ``jitted.lower(...).as_text()`` (StableHLO) or
    ``.lower(...).compile().as_text()`` (optimized HLO). Bytes are the
    per-invocation result-buffer sizes — i.e. what one execution of the
    program moves across the collective, not link-level wire bytes
    (which depend on the algorithm XLA picks). NOTE: a GSPMD program
    (jit + shardings, no shard_map) keeps its collectives implicit until
    XLA's SPMD partitioner runs, so its StableHLO reports 0 — pass the
    COMPILED text to count those. Returns
    {"ops": {op_name: count}, "bytes": {op_name: bytes},
    "bytes_by_dtype": {canonical_dtype: bytes},
    "bytes_by_kind_dtype": {op_name: {canonical_dtype: bytes}},
    "total_bytes"} — the per-dtype split is what makes a
    quantized-collective experiment (distributed/qcomm.py) readable
    straight off the gauges instead of derived from op-level deltas,
    and the per-kind×per-dtype split is what separates the ring's two
    halves (reduce-scatter vs all-gather) for the ZeRO ledger.
    """
    ops: dict = {}
    byts: dict = {}
    by_dtype: dict = {}
    by_kind_dtype: dict = {}

    def _acc(op: str, dims: str, dtype: str) -> None:
        b = _tensor_bytes(dims, dtype)
        byts[op] = byts.get(op, 0) + b
        canon = _DTYPE_CANON.get(dtype, dtype)
        by_dtype[canon] = by_dtype.get(canon, 0) + b
        kd = by_kind_dtype.setdefault(op, {})
        kd[canon] = kd.get(canon, 0) + b

    lines = lowered_text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        m = _COLLECTIVE_RE.search(line)
        if not m:
            hm = _HLO_COLLECTIVE_RE.search(line)
            if hm:
                op = hm.group(2).replace("-", "_")
                ops[op] = ops.get(op, 0) + 1
                for dt, dims in _HLO_TYPE_RE.findall(hm.group(1)):
                    _acc(op, dims.replace(",", "x"), dt)
            i += 1
            continue
        op = m.group(1)
        ops[op] = ops.get(op, 0) + 1
        # Region-bearing collectives (all_reduce / reduce_scatter carry
        # their reduction computation as a region) print the function
        # type on the region's CLOSING `}) : (...) -> ...` line; reading
        # the op line instead would pick up the replica_groups attribute
        # type (tensor<NxMxi64>).
        type_line = line
        if line.rstrip().endswith("({"):
            j = i + 1
            while j < len(lines):
                if lines[j].lstrip().startswith("})"):
                    type_line = lines[j]
                    i = j
                    break
                j += 1
        am = _ARROW_RE.search(type_line)
        tensors = _TENSOR_RE.findall(am.group(1)) if am else []
        if tensors:
            # after `->`: the result type(s); variadic collectives print
            # a tuple `(tensor<..>, tensor<..>)` — sum every buffer
            for d, t in tensors:
                _acc(op, d, t)
        else:
            # compact printer form has no arrow (`... applies stablehlo.add
            # : tensor<..>`): last tensor type on the line is the result
            tensors = _TENSOR_RE.findall(type_line)
            if tensors:
                dims, dt = tensors[-1]
                _acc(op, dims, dt)
        i += 1
    return {"ops": ops, "bytes": byts, "bytes_by_dtype": by_dtype,
            "bytes_by_kind_dtype": by_kind_dtype,
            "total_bytes": sum(byts.values())}


#: The ring's two halves, as gauge buckets over lowered op kinds. The
#: manual ring's reduce-scatter half lowers to ``collective_permute``
#: hops (ppermute) while GSPMD's spelling is a real ``reduce_scatter``
#: op — both are grad-sharding traffic, so they share the bucket.
#: ``all_reduce`` is deliberately in NEITHER: it is the fused
#: both-halves op, so a replicated AllReduce program reads 0 on both
#: half-gauges and the split stays strictly "ring halves".
_KIND_BUCKETS = {
    "reduce_scatter": ("reduce_scatter", "collective_permute"),
    "all_gather": ("all_gather",),
}
#: gauge-suffix -> canonical parsed dtypes folded into it
_DTYPE_BUCKETS = {"int8": ("i8", "ui8"), "bf16": ("bf16",),
                  "f32": ("f32",)}


def record_collective_stats(lowered_text: str, prefix: str = "comm") -> dict:
    """collective_stats + fold the totals into the metrics registry.

    Besides the blended total, the per-dtype gauges
    ``{prefix}/collective_bytes_int8`` / ``_f32`` make the "collective
    bytes halved" claim of a quantized-AllReduce config (qcomm.py)
    readable straight off the gauge: int8 counts the i8/ui8 payloads,
    f32 the f32 ones (block scales included — they ARE f32 wire
    bytes). The per-kind×per-dtype gauges
    ``{prefix}/collective_bytes_{reduce_scatter,all_gather}_{int8,
    bf16,f32}`` additionally split the ring's two halves (ZeRO's grad
    sharding vs param return, ISSUE 19) so "the sharded arm moved its
    gradient bytes over reduce-scatter" is a registry read, not an HLO
    diff."""
    st = collective_stats(lowered_text)
    reg = registry()
    reg.gauge(f"{prefix}/collective_bytes_per_step").set(st["total_bytes"])
    reg.gauge(f"{prefix}/collective_ops_per_step").set(
        sum(st["ops"].values()))
    bd = st["bytes_by_dtype"]
    reg.gauge(f"{prefix}/collective_bytes_int8").set(
        bd.get("i8", 0) + bd.get("ui8", 0))
    reg.gauge(f"{prefix}/collective_bytes_f32").set(bd.get("f32", 0))
    bkd = st["bytes_by_kind_dtype"]
    for kind, opnames in _KIND_BUCKETS.items():
        for sfx, canons in _DTYPE_BUCKETS.items():
            total = sum(bkd.get(op, {}).get(c, 0)
                        for op in opnames for c in canons)
            reg.gauge(
                f"{prefix}/collective_bytes_{kind}_{sfx}").set(total)
    return st


def record_collectives_from(lowered, mesh=None, prefix: str = "comm") -> dict:
    """record_collective_stats over a ``jax.stages.Lowered``, with the
    GSPMD fallback: when the StableHLO shows ZERO collectives on a
    multi-device mesh, parse the partitioned (compiled) program instead
    — GSPMD keeps its collectives implicit until XLA's SPMD partitioner,
    and only paying the extra compile in that case keeps shard_map
    programs cheap. (A mixed shard_map+GSPMD program whose StableHLO
    already shows some collectives skips the fallback and undercounts
    the implicit ones — callers wanting exact mixed accounting must pass
    compiled text to record_collective_stats themselves.)"""
    text = lowered.as_text()
    if not collective_stats(text)["ops"] and mesh is not None \
            and mesh.devices.size > 1:
        text = lowered.compile().as_text()
    return record_collective_stats(text, prefix)


def device_memory_stats(device=None) -> Optional[dict]:
    """``Device.memory_stats()`` of the first (or given) local device;
    None where the backend does not report (CPU, some remote tunnels)."""
    try:
        d = device or jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return dict(stats)


def record_memory_high_water(prefix: str = "memory") -> Optional[int]:
    """Record the device-memory high-water mark (bytes) as a max-gauge.
    Returns the current peak or None when the backend has no stats."""
    st = device_memory_stats()
    if st is None:
        return None
    peak = st.get("peak_bytes_in_use", st.get("bytes_in_use"))
    if peak is None:
        return None
    reg = registry()
    reg.gauge(f"{prefix}/peak_bytes_in_use").set_max(int(peak))
    if "bytes_in_use" in st:
        reg.gauge(f"{prefix}/bytes_in_use").set(int(st["bytes_in_use"]))
    return int(peak)


def _per_rank_bytes(v) -> int:
    """Per-rank resident bytes of one ledger entry: a pytree of arrays
    (each counted at its PER-DEVICE shard shape via
    ``sharding.shard_shape`` — a dp-sharded ZeRO slab counts 1/dp of
    its global size, a replicated param counts in full) or a plain int
    (pre-computed bytes, e.g. a transient gradient buffer that never
    materializes as a persistent array)."""
    if isinstance(v, (int, float)) and not hasattr(v, "shape"):
        return int(v)
    total = 0
    for a in jax.tree_util.tree_leaves(v):
        shape = getattr(a, "shape", ())
        sharding = getattr(a, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass
        n = 1
        for d in shape:
            n *= int(d)
        total += n * int(getattr(getattr(a, "dtype", None), "itemsize",
                                 None) or np.dtype(
                                     getattr(a, "dtype", "float32")
                                 ).itemsize)
    return total


def record_memory_ledger(categories: dict, prefix: str = "mem") -> dict:
    """The ZeRO memory ledger (ISSUE 19): per-rank resident bytes per
    state category, computed from ACTUAL array shardings — not a
    model. ``categories`` maps a name (``param`` / ``grad`` /
    ``opt_state`` / ``master``...) to a pytree of arrays or a raw byte
    count; each is folded into the ``{prefix}/{name}_bytes`` gauge
    (and thus ``profiler.summary()``, the Prometheus sink, and bench
    blocks). Returns ``{name: bytes}``. This is the gauge pair that
    states the ZeRO claim: sharded ``opt_state_bytes`` ≈ 1/dp of the
    replicated baseline's."""
    reg = registry()
    out = {}
    for name, v in categories.items():
        b = _per_rank_bytes(v)
        out[name] = b
        reg.gauge(f"{prefix}/{name}_bytes").set(b)
    return out


# Nominal interconnect bandwidth (bytes/s, per direction) used by the
# comm-phase MODEL below. v5e ICI is ~45 GB/s/link; the CPU figure is a
# loopback placeholder so the model degrades to ~0 on test platforms.
_LINK_BW = {"tpu": 45e9, "cpu": 10e9}


def estimate_comm_ms(total_bytes: int, platform: str = "tpu") -> float:
    """Lower-bound comm-phase time from collective bytes over the nominal
    interconnect bandwidth. A MODEL, not a measurement: XLA overlaps
    collectives with compute and picks algorithms that change wire bytes;
    this answers "how long would the bytes alone take at link rate" —
    0 for a program with no collectives (single chip)."""
    return total_bytes / _LINK_BW.get(platform, _LINK_BW["tpu"]) * 1e3


def _first_leaf(o) -> float:
    return float(np.asarray(jax.tree_util.tree_leaves(o)[0]).ravel()[0])


def time_compiled(fn, iters: int = 2) -> float:
    """Mean seconds per call of ``fn`` (a thunk running a jitted
    program): one call to compile + warm, then ``iters`` timed calls
    ended by a host fetch of the first output leaf — the only truthful
    sync point under async dispatch. Shared by every
    ``profile_step_phases`` so the phase numbers trainers report stay
    comparable."""
    _first_leaf(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _first_leaf(out)
    return (time.perf_counter() - t0) / iters


def record_phases(fwd_s=None, fwdbwd_s=None, step_s=None,
                  comm_bytes=None, platform: str = "tpu",
                  cost_bytes_accessed=None) -> dict:
    """Fold a phase decomposition (seconds; any may be None) into the
    ``phase/*_ms`` gauges the profiler summary reports.

    The step is ONE fused XLA program, so trainers time nested prefixes
    (fwd-only, fwd+bwd, full step) and this derives
    bwd = fwdbwd − fwd, optim = step − fwdbwd. Returns the phases dict
    (ms).

    The comm phase is an honest two-number split, not one blended guess:

    - ``comm_ms`` — the nominal-bandwidth MODEL (estimate_comm_ms):
      collective bytes over link rate, ignoring overlap. Kept for
      continuity and as a lower bound on the unoverlapped cost.
    - ``comm_measured_ms`` — measured step wall time apportioned by
      XLA's own byte accounting (``cost_bytes_accessed`` from
      ``compiled.cost_analysis()``): ``step_ms * collective_bytes /
      bytes_accessed``. The wall clock is real; the ATTRIBUTION assumes
      collective bytes cost what average program bytes cost — truthful
      about magnitude on memory-bound steps, silent about overlap.
      Recorded only when the caller has cost analysis (xla_stats).
    """
    reg = registry()
    out = {}
    if fwd_s is not None:
        out["fwd_ms"] = fwd_s * 1e3
    if fwdbwd_s is not None and fwd_s is not None:
        out["bwd_ms"] = max(fwdbwd_s - fwd_s, 0.0) * 1e3
    if step_s is not None:
        out["step_ms"] = step_s * 1e3
        if fwdbwd_s is not None:
            out["optim_ms"] = max(step_s - fwdbwd_s, 0.0) * 1e3
    if comm_bytes is not None:
        out["comm_ms"] = estimate_comm_ms(comm_bytes, platform)
        if step_s is not None and cost_bytes_accessed:
            share = min(float(comm_bytes) / float(cost_bytes_accessed),
                        1.0)
            out["comm_measured_ms"] = step_s * 1e3 * share
    for k, v in out.items():
        reg.gauge(f"phase/{k[:-3]}_ms").set(round(v, 4))
    return {k: round(v, 4) for k, v in out.items()}


def tokens_in_batch(batch) -> int:
    """Throughput accounting for a step's batch: ``batch*seq`` when the
    first array-like argument is a 2-d INTEGER array (a token grid),
    else its ``batch`` dim (sample count — a [N,C,H,W] image batch must
    not scale with channels). Labels/aux inputs ride dim-0-aligned with
    the first, so the first is the truthful count."""
    for b in batch:
        shape = getattr(b, "shape", None)
        if shape is None or len(shape) == 0:
            continue
        if len(shape) == 2 and "int" in str(getattr(b, "dtype", "")):
            return int(shape[0]) * int(shape[1])
        return int(shape[0])
    return 0
