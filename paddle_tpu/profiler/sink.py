"""Persistent metrics sink: the registry + event log, on disk.

Until this module, every metric and event died with the process — a
watchdog abort, a SIGTERM preemption, or a plain crash left NOTHING to
read. The sink is a background writer that periodically (and
deterministically at every exit edge) flushes:

- ``metrics.jsonl`` — one JSON line per flush: timestamp, reason,
  ``events_lost`` (events that aged out of the ring before this flush
  could persist them — a sustained emit rate above capacity/interval
  shows up HERE, not as silence) and the full registry snapshot
  (append-only, crash-tolerant: a torn last line loses one flush,
  never the file);
- ``events.jsonl`` — the event log streamed exactly once via a
  sequence cursor (one JSON object per event, append-only; the cursor
  advances only after a successful append, so an I/O error re-sends
  the WHOLE segment next flush — at-least-once under errors. A
  partially-landed segment therefore leaves a damaged file: a torn
  line and/or duplicate seqs that tools/check_sink_schema.py flags by
  design — the deliberate trade is that write failures surface in
  validation rather than events silently vanishing. Ring-overflow
  losses (events aged out between flushes) appear as seq GAPS here and
  are counted per flush in metrics.jsonl's ``events_lost``);
- ``metrics.prom`` — the LATEST snapshot in Prometheus textfile-
  collector format, rewritten atomically (tmp + rename) so a scraper
  never reads a half-written file.

Flush edges, all carrying a ``reason`` in the metrics line:

- ``interval`` — the background thread, every ``interval_s``;
- ``exit`` — ``close()``; ``enable_sink`` registers an atexit hook so
  a normal interpreter exit always flushes;
- ``preempt`` — the resilience runner flushes after the SIGTERM
  preemption checkpoint commits (riding the PR 2 preemption path; the
  signal handler itself stays async-signal-trivial);
- ``watchdog`` — StepWatchdog._fire flushes BEFORE an abort's
  ``os._exit`` (which skips atexit by design);
- ``rollback`` — the resilient runner's bad-step rollback, before the
  restore overwrites the state the telemetry describes;
- ``reset`` — ``profiler.enable(reset=True)`` / ``profiler.reset()``
  drain the event ring into the sink before emptying it.

Every flush also ``mark()``s the flight recorder, so a later dump's
metric deltas read "since the last flush" — the incident window.

One sink is active per process (``enable_sink`` replaces and closes a
prior one). A new sink rotates any pre-existing ``metrics.jsonl`` /
``events.jsonl`` aside (first free ``.N`` suffix): each sink session
owns fresh files whose flush_seq/seq start at this session's values,
so reusing a ``--sink-dir`` across runs keeps every file individually
schema-valid and old post-mortems readable. The writer thread holds no
jax state and issues no collectives — pure host I/O, safe next to XLA
(SaveHandle rule).

Multi-process safety (ISSUE 13): every metrics line and every event
line carries the writing process's ``rank`` (the jax process index; 0
single-process), and on a multi-process mesh ``enable_sink`` redirects
each rank into its own ``rank<K>/`` subdirectory of the requested path
— N processes NEVER append to one file, so there are no torn
interleaved lines by construction (POSIX O_APPEND would interleave
whole lines at best, and the per-file strictly-increasing seq contract
cannot survive two writers at all). A mesh-level consumer globs
``<dir>/rank*/events.jsonl`` and has the rank field on every line to
group by; tools/check_sink_schema.py validates the field and flags a
file whose rank stamps disagree (two writers sharing a file IS the
bug the field exists to catch).
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from typing import Dict, Optional

from . import disttrace as _disttrace
from . import events as _events
from .metrics import registry

__all__ = ["MetricsSink", "enable_sink", "disable_sink", "active_sink",
           "flush_active", "prometheus_text", "stats"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(prefix: str, name: str) -> str:
    return _NAME_RE.sub("_", f"{prefix}_{name}")


def _rotate(path: str) -> None:
    """Move a non-empty artifact from an earlier sink session aside —
    appending this session's seq-0 lines after it would break the
    per-file strictly-increasing flush_seq/seq contract the schema
    validator enforces."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return
    k = 1
    while os.path.exists(f"{path}.{k}"):
        k += 1
    os.replace(path, f"{path}.{k}")


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prometheus_text(snapshot: Dict[str, dict],
                    prefix: str = "paddle_tpu") -> str:
    """Registry snapshot -> Prometheus textfile exposition. Counters
    get the conventional ``_total`` suffix; histograms export as
    summaries (count/sum + p50/p90/p95/p99 quantile samples from the
    bounded reservoir — rank-local, like the snapshot itself)."""
    lines = []
    for name in sorted(snapshot):
        s = snapshot[name]
        typ = s.get("type")
        if typ == "counter":
            n = _prom_name(prefix, name) + "_total"
            lines += [f"# TYPE {n} counter", f"{n} {_fmt(s['value'])}"]
        elif typ == "gauge":
            if s.get("value") is None:
                continue
            n = _prom_name(prefix, name)
            lines += [f"# TYPE {n} gauge", f"{n} {_fmt(s['value'])}"]
        elif typ == "histogram":
            n = _prom_name(prefix, name)
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_count {_fmt(s.get('count', 0))}")
            if s.get("count"):
                lines.append(f"{n}_sum {_fmt(s['sum'])}")
                for q, key in ((0.5, "p50"), (0.9, "p90"),
                               (0.95, "p95"), (0.99, "p99")):
                    if s.get(key) is not None:
                        lines.append(
                            f'{n}{{quantile="{q}"}} {_fmt(s[key])}')
    return "\n".join(lines) + "\n"


class MetricsSink:
    """See module docstring. ``start()`` launches the interval thread;
    ``flush(reason)`` is safe from any thread; ``close()`` is
    idempotent and always ends with a final flush."""

    def __init__(self, directory: str, interval_s: float = 10.0,
                 prefix: str = "paddle_tpu",
                 metrics_file: str = "metrics.jsonl",
                 events_file: str = "events.jsonl",
                 prom_file: str = "metrics.prom",
                 event_log: Optional[_events.EventLog] = None,
                 rank: Optional[int] = None,
                 frames: bool = True, frame_keep: int = 16):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if frame_keep < 2:
            raise ValueError("frame_keep must be >= 2")
        self.rank = _detect_rank() if rank is None else int(rank)
        if self.rank < 0:
            raise ValueError("rank must be >= 0")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.interval_s = float(interval_s)
        self.prefix = prefix
        self._metrics_path = os.path.join(directory, metrics_file)
        self._events_path = os.path.join(directory, events_file)
        self._prom_path = os.path.join(directory, prom_file)
        _rotate(self._metrics_path)   # prom is rewritten atomically —
        _rotate(self._events_path)    # latest-wins is its contract
        self._event_log = event_log or _events.log()
        self._cursor = 0           # event-log seq already persisted
        self._flushes = 0
        self._flush_errors = 0     # failed/timed-out flush attempts
        self._last_error: Optional[str] = None
        # telemetry frames (ISSUE 16): every flush additionally
        # publishes an atomic per-rank frame the LiveAggregator tails
        self.frames = bool(frames)
        self._frames_dir = os.path.join(directory, "frames")
        self._frame_keep = int(frame_keep)
        self._frames_written = 0
        self._frame_errors = 0     # failed publications (fire-and-
        self._prev_counters: Dict[str, float] = {}  # forget, counted)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MetricsSink":
        if self._thread is None and not self._closed:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="profiler-sink", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush("interval")
            except Exception:  # keep the writer alive; the failure is
                pass           # already counted by flush()

    def close(self, reason: str = "exit",
              timeout: Optional[float] = None) -> None:
        """``timeout`` bounds each lock wait (same contract as
        ``flush``): a writer thread wedged in hung I/O must not hang
        process exit — the atexit hook passes one, skipping the final
        flush rather than blocking forever."""
        if not self._lock.acquire(timeout=-1 if timeout is None
                                  else timeout):
            self._closed = True       # wedged writer: give up the flush
            self._flush_errors += 1
            self._last_error = f"close({reason!r}): lock timeout"
            self._stop.set()
            return
        try:
            if self._closed:
                return
            self._closed = True
        finally:
            self._lock.release()
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._thread = None
        if not self._lock.acquire(timeout=-1 if timeout is None
                                  else timeout):
            return
        try:
            self._flush_locked(reason)
        finally:
            self._lock.release()

    def __enter__(self) -> "MetricsSink":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- flushing ----------------------------------------------------------
    def flush(self, reason: str = "manual",
              timeout: Optional[float] = None) -> Optional[dict]:
        """``timeout`` makes the flush best-effort: if the writer lock
        cannot be acquired in time (the interval thread wedged in hung
        I/O while holding it), return None instead of blocking. The
        watchdog's fire path uses this — a stuck flush must never stand
        between the watchdog and its abort ``os._exit``."""
        if not self._lock.acquire(timeout=-1 if timeout is None
                                  else timeout):
            # a wedged writer IS a flush failure: data the caller asked
            # to persist did not land — count it so summary() shows it
            self._flush_errors += 1
            self._last_error = f"flush({reason!r}): lock timeout"
            return None
        try:
            if self._closed:
                return None
            try:
                return self._flush_locked(reason)
            except Exception as e:
                self._flush_errors += 1
                self._last_error = \
                    f"flush({reason!r}): {type(e).__name__}: {e}"
                raise
        finally:
            self._lock.release()

    def _flush_locked(self, reason: str) -> dict:
        with self._lock:
            snap = registry().snapshot()
            # stamp-then-increment BEFORE any I/O: a flush that dies
            # mid-write leaves a GAP in flush_seq, never a duplicate
            # (the schema validator requires strictly-increasing seqs)
            seq = self._flushes
            self._flushes += 1
            evs, cursor = self._event_log.since(self._cursor)
            # ring overflow between flushes ages events out before they
            # persist: the segment then starts past the cursor (or the
            # cursor jumps with no events at all). Count the gap — the
            # loss lands in this flush's metrics line, never silent.
            first = evs[0].seq if evs else cursor
            lost = max(0, first - self._cursor)
            if evs:
                # rank-stamped at write: events are process-local, so
                # the writer's rank IS the event's rank
                seg = "".join(
                    json.dumps({**ev.to_dict(), "rank": self.rank})
                    + "\n" for ev in evs)
                with open(self._events_path, "a") as f:
                    f.write(seg)
            elif not os.path.exists(self._events_path):
                # schema contract: the file exists even before the
                # first event (a validator must not special-case it)
                open(self._events_path, "a").close()
            # the cursor advances only once the segment hit the file —
            # an I/O error above re-sends it on the next flush
            self._cursor = cursor
            # cross-host tracing metadata (ISSUE 14): (clock.wall_s,
            # t_ns) is this rank's wall-clock anchor — the pair is
            # read back-to-back, so an offline consumer can place any
            # event's perf_counter t_ns on this rank's wall clock;
            # offset_s/unc_s are the agreed clock alignment (relative
            # to clock.ref) tools/merge_traces.py corrects with.
            # clock.wall_s deliberately comes from disttrace.walltime
            # (ts below stays the process's REAL time): an injected
            # test skew must reach the anchor, or the mesh tests could
            # not prove the offset correction recovers it.
            # the wall read is BRACKETED by two monotonic reads: the
            # midpoint pairs the clocks to first order even if the
            # thread is preempted between the reads, and the half-gap
            # is stamped as anchor_unc_s so the merger can widen its
            # slack instead of flagging a phantom ordering violation
            t_a = time.perf_counter_ns()
            wall = _disttrace.walltime()
            t_b = time.perf_counter_ns()
            t_ns = (t_a + t_b) // 2
            clock = dict(_disttrace.clock_state(),
                         wall_s=round(wall, 6),
                         anchor_unc_s=round((t_b - t_a) / 2e9, 9))
            line = {"ts": round(time.time(), 6), "reason": reason,
                    "rank": self.rank, "flush_seq": seq,
                    "t_ns": t_ns, "clock": clock,
                    "events_lost": lost, "metrics": snap}
            with open(self._metrics_path, "a") as f:
                f.write(json.dumps(line) + "\n")
            tmp = self._prom_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(prometheus_text(snap, self.prefix))
            os.replace(tmp, self._prom_path)
            # telemetry frame (ISSUE 16): fire-and-forget — a dead or
            # slow aggregator-side filesystem must never fail the flush
            # the metrics line above already committed
            if self.frames:
                try:
                    self._publish_frame(seq, line, snap)
                except Exception as e:
                    self._frame_errors += 1
                    self._last_error = \
                        f"frame({seq}): {type(e).__name__}: {e}"
            # deltas in a later flight dump read "since the last flush"
            _events.flight_recorder().mark()
            return line

    def _publish_frame(self, seq: int, line: dict,
                       snap: Dict[str, dict]) -> None:
        """Write ``frames/rank<K>-<seq>.json`` atomically (tmp +
        rename, the consensus-board idiom): cumulative counters with
        deltas-since-last-frame, last-value gauges, CUMULATIVE sketch
        buckets (cross-rank merge stays exact; a lost frame costs
        nothing — the next one carries the full state), this flush's
        clock anchor, and the consensus epochs this rank adopted. Old
        frames beyond ``frame_keep`` are pruned — the frames dir is a
        rolling tail, not an archive (metrics.jsonl is the archive)."""
        counters: Dict[str, dict] = {}
        gauges: Dict[str, Optional[float]] = {}
        new_prev: Dict[str, float] = {}
        for name, s in snap.items():
            if s.get("type") == "counter":
                v = float(s["value"] or 0.0)
                counters[name] = {
                    "v": v,
                    "d": round(v - self._prev_counters.get(name, 0.0),
                               9)}
                new_prev[name] = v
            elif s.get("type") == "gauge":
                gauges[name] = s["value"]
        epochs: Dict[str, int] = {}
        try:
            from ..distributed.consensus import adopted_epochs
            epochs = dict(adopted_epochs())
        except Exception:  # pragma: no cover - consensus unavailable
            pass
        frame = {"kind": "telemetry_frame", "rank": self.rank,
                 "seq": seq, "ts": line["ts"], "t_ns": line["t_ns"],
                 "clock": line["clock"],
                 "events_lost": line["events_lost"],
                 "adopted_epochs": epochs, "counters": counters,
                 "gauges": gauges,
                 "sketches": registry().sketch_dicts()}
        os.makedirs(self._frames_dir, exist_ok=True)
        name = f"rank{self.rank}-{seq}.json"
        tmp = os.path.join(self._frames_dir, f".{name}.tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps(frame))
        os.replace(tmp, os.path.join(self._frames_dir, name))
        # deltas advance only once the frame LANDED — a failed write
        # re-bases the next frame's "d" on the last published one
        self._prev_counters.update(new_prev)
        self._frames_written += 1
        floor = seq - self._frame_keep
        if floor >= 0:
            pat = re.compile(rf"^rank{self.rank}-(\d+)\.json$")
            for fn in os.listdir(self._frames_dir):
                m = pat.match(fn)
                if m and int(m.group(1)) <= floor:
                    try:
                        os.remove(os.path.join(self._frames_dir, fn))
                    except OSError:  # pragma: no cover - racing reader
                        pass

    @property
    def flushes(self) -> int:
        return self._flushes

    @property
    def flush_errors(self) -> int:
        """Flush attempts that failed (I/O error or lock timeout) —
        surfaced in-process via ``profiler.summary()["sink"]``, not
        just implied by holes in the on-disk artifacts."""
        return self._flush_errors

    @property
    def frames_written(self) -> int:
        return self._frames_written

    @property
    def frame_errors(self) -> int:
        """Telemetry-frame publications that failed (counted, never
        raised — a dead aggregator-side filesystem must not block the
        serving process's flush path)."""
        return self._frame_errors

    @property
    def last_error(self) -> Optional[str]:
        return self._last_error


# ---------------------------------------------------------------------------
# process-global active sink
# ---------------------------------------------------------------------------
_active: Optional[MetricsSink] = None
_atexit_registered = False


def _atexit_close() -> None:  # pragma: no cover - interpreter teardown
    s = _active
    if s is not None:
        try:
            # bounded: a writer wedged in hung I/O (holding the flush
            # lock) must not hang interpreter exit
            s.close("exit", timeout=10.0)
        except Exception:
            pass


def _detect_rank() -> int:
    """The jax process index, without forcing backend bring-up when
    jax.distributed was never initialized (0 then)."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return 0
        import jax

        return int(jax.process_index())
    except Exception:  # pragma: no cover - exotic bring-up failure
        return 0


def _detect_world() -> int:
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return 1
        import jax

        return int(jax.process_count())
    except Exception:  # pragma: no cover
        return 1


def enable_sink(directory: str, per_rank_subdir: Optional[bool] = None,
                **kwargs) -> MetricsSink:
    """Create + start the process's active sink (closing any prior
    one) and register the exit flush. kwargs ride to MetricsSink.

    On a multi-process mesh each rank's artifacts land in
    ``<directory>/rank<K>/`` (``per_rank_subdir``: None = auto, on
    exactly when the jax world has more than one process) — N
    processes never share a JSONL file, so no interleaved/torn lines
    and the per-file seq contract survives."""
    global _active, _atexit_registered
    if _active is not None:
        _active.close("replaced")
    rank = kwargs.get("rank")
    if rank is None:
        rank = _detect_rank()
        kwargs["rank"] = rank
    if per_rank_subdir is None:
        per_rank_subdir = _detect_world() > 1
    if per_rank_subdir:
        directory = os.path.join(directory, f"rank{rank}")
    _active = MetricsSink(directory, **kwargs).start()
    if not _atexit_registered:
        atexit.register(_atexit_close)
        _atexit_registered = True
    return _active


def disable_sink(reason: str = "disabled") -> None:
    global _active
    if _active is not None:
        _active.close(reason)
        _active = None


def active_sink() -> Optional[MetricsSink]:
    return _active


def stats() -> dict:
    """In-process sink health: {active, directory, flushes,
    flush_errors, last_error} — what ``profiler.summary()`` embeds so
    a failing writer is visible BEFORE anyone reads metrics.jsonl."""
    s = _active
    if s is None:
        return {"active": False, "flushes": 0, "flush_errors": 0,
                "frames": 0, "frame_errors": 0, "last_error": None}
    return {"active": True, "directory": s.directory,
            "flushes": s.flushes, "flush_errors": s.flush_errors,
            "frames": s.frames_written, "frame_errors": s.frame_errors,
            "last_error": s.last_error}


def flush_active(reason: str,
                 timeout: Optional[float] = None) -> Optional[dict]:
    """Flush the active sink if there is one; never raises (called
    from watchdog fires and preemption paths). ``timeout`` bounds the
    wait for a wedged writer — see MetricsSink.flush."""
    s = _active
    if s is None:
        return None
    try:
        return s.flush(reason, timeout=timeout)
    except Exception:  # pragma: no cover - post-mortem shield
        return None
