"""Mergeable quantile sketch — the live telemetry plane's distribution
primitive (ISSUE 16).

The Histogram reservoir this replaces kept "the most recent 1024
observations" per rank and cross-rank aggregation NaN-pad-allgathered
the raw samples: an approximation whose error was *unstated* (whatever
the window happened to hold) and whose merge cost grew with the sample
count. This module is the DDSketch construction instead (Masson et al.,
VLDB'19 — the datadog sketch serving dashboards actually run on):

- **relative-error buckets**: value ``v > 0`` lands in bucket
  ``ceil(log_gamma(v))`` with ``gamma = (1 + a) / (1 - a)`` for a
  configured relative accuracy ``a`` (default 1%). Reporting a bucket's
  geometric midpoint guarantees ``|est - v| <= a * v`` for EVERY
  quantile — a stated, uniform bound, not a sampling accident.
- **bounded size**: at most ``max_buckets`` buckets per sign; overflow
  collapses the LOWEST buckets together (the DDSketch rule: quantiles
  ABOVE the collapsed floor — the tail an SLO quotes — keep the full
  bound; everything folded below it is degraded and the folded count
  is surfaced as ``collapsed``, never hidden). 2048 buckets at 1%
  span ~ 17 orders of magnitude of value, so on any physical latency
  stream collapse is a pathology flag, not a code path.
- **exact merge**: two sketches with the same ``gamma`` merge by
  bucket-wise ADDITION — associative, commutative, lossless. A mesh's
  p95 computed from merged rank sketches is EXACTLY the p95 the union
  sketch would have produced; there is no cross-rank approximation
  left to state. ``subtract`` gives windowed deltas between two
  cumulative snapshots of the SAME stream the same way.

Count / sum / min / max stay exact (the old Histogram contract).
Percentiles follow the repo's nearest-rank convention over bucket
counts and are clamped into ``[min, max]``, so tiny sketches behave
sanely. Serialization (``to_dict``/``from_dict``) is pure-JSON — the
telemetry frames ride it; keys are stringified ints because JSON
object keys are strings.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

__all__ = ["QuantileSketch", "DEFAULT_REL_ERR"]

#: default relative accuracy: 1% — the documented bound mesh_status
#: quotes and the live-vs-offline agreement tests assert against
DEFAULT_REL_ERR = 0.01


class QuantileSketch:
    """DDSketch-style mergeable quantile sketch. Not thread-safe —
    Histogram wraps every touch in its own lock."""

    __slots__ = ("rel_err", "gamma", "_lg", "max_buckets", "_pos",
                 "_neg", "_zero", "_n", "_sum", "_min", "_max",
                 "collapsed")

    def __init__(self, rel_err: float = DEFAULT_REL_ERR,
                 max_buckets: int = 2048):
        if not 0.0 < rel_err < 1.0:
            raise ValueError("rel_err must be in (0, 1)")
        if max_buckets < 2:
            raise ValueError("max_buckets must be >= 2")
        self.rel_err = float(rel_err)
        self.gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._lg = math.log(self.gamma)
        self.max_buckets = int(max_buckets)
        self._pos: Dict[int, int] = {}   # bucket index -> count (v>0)
        self._neg: Dict[int, int] = {}   # mirrored buckets for v<0
        self._zero = 0                   # exact-zero count
        self._n = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        #: number of observations folded into a floor bucket by the
        #: bounded-size collapse (0 on every healthy stream)
        self.collapsed = 0

    # -- ingest ------------------------------------------------------------
    def _index(self, v: float) -> int:
        # gamma^(i-1) < v <= gamma^i; the +eps-free ceil form is exact
        # enough: a boundary landing one bucket over still satisfies
        # the relative-error bound by construction
        return int(math.ceil(math.log(v) / self._lg))

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return
        self._n += 1
        self._sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        if v > 0.0:
            i = self._index(v)
            self._pos[i] = self._pos.get(i, 0) + 1
            if len(self._pos) > self.max_buckets:
                self._collapse(self._pos)
        elif v < 0.0:
            i = self._index(-v)
            self._neg[i] = self._neg.get(i, 0) + 1
            if len(self._neg) > self.max_buckets:
                self._collapse(self._neg)
        else:
            self._zero += 1

    def _collapse(self, buckets: Dict[int, int]) -> None:
        """Fold the lowest buckets into one floor bucket until the
        bound holds — tail accuracy (the quoted quantiles) survives;
        the folded count is surfaced in ``collapsed``."""
        keys = sorted(buckets)
        while len(buckets) > self.max_buckets:
            lo = keys.pop(0)
            c = buckets.pop(lo)
            buckets[keys[0]] = buckets.get(keys[0], 0) + c
            self.collapsed += c

    # -- read --------------------------------------------------------------
    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> Optional[float]:
        return None if self._n == 0 else self._min

    @property
    def max(self) -> Optional[float]:
        return None if self._n == 0 else self._max

    def _bucket_value(self, i: int) -> float:
        # geometric midpoint of (gamma^(i-1), gamma^i]: worst-case
        # relative error a = (gamma - 1) / (gamma + 1) = rel_err
        return 2.0 * self.gamma ** i / (self.gamma + 1.0)

    def _ascending(self) -> List[Tuple[float, int]]:
        out = [(-self._bucket_value(i), self._neg[i])
               for i in sorted(self._neg, reverse=True)]
        if self._zero:
            out.append((0.0, self._zero))
        out.extend((self._bucket_value(i), self._pos[i])
                   for i in sorted(self._pos))
        return out

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile estimate (the repo convention:
        rank ``min(int(q/100 * n), n - 1)`` over the sorted stream),
        within ``rel_err`` relative error, clamped into [min, max]."""
        if self._n == 0:
            return None
        rank = min(int(q / 100.0 * self._n), self._n - 1)
        seen = 0
        est = self._max
        for v, c in self._ascending():
            seen += c
            if seen > rank:
                est = v
                break
        return min(max(est, self._min), self._max)

    def snapshot(self) -> dict:
        """Histogram-snapshot-shaped summary (the keys sink/prom/bench
        consumers already read)."""
        if self._n == 0:
            return {"type": "histogram", "count": 0}
        return {"type": "histogram", "count": self._n,
                "sum": self._sum, "mean": self._sum / self._n,
                "min": self._min, "max": self._max,
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p95": self.percentile(95), "p99": self.percentile(99)}

    # -- merge / window ----------------------------------------------------
    def _check_compatible(self, other: "QuantileSketch") -> None:
        if abs(other.rel_err - self.rel_err) > 1e-12:
            raise ValueError(
                f"cannot combine sketches with rel_err "
                f"{self.rel_err} vs {other.rel_err}")

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (bucket-wise add — exact). Returns
        self for chaining."""
        self._check_compatible(other)
        for i, c in other._pos.items():
            self._pos[i] = self._pos.get(i, 0) + c
        for i, c in other._neg.items():
            self._neg[i] = self._neg.get(i, 0) + c
        self._zero += other._zero
        self._n += other._n
        self._sum += other._sum
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        self.collapsed += other.collapsed
        if len(self._pos) > self.max_buckets:
            self._collapse(self._pos)
        if len(self._neg) > self.max_buckets:
            self._collapse(self._neg)
        return self

    def subtract(self, older: "QuantileSketch") -> "QuantileSketch":
        """Windowed delta between two CUMULATIVE snapshots of the same
        stream (``self`` newer): bucket-wise subtraction, floored at 0
        (a collapse between the snapshots can shift counts across
        buckets — floor, never guess negatives). The window's min/max
        are unknowable from buckets alone, so they are the delta's
        bucket-implied bounds — honest to within ``rel_err``."""
        self._check_compatible(older)
        out = QuantileSketch(self.rel_err, self.max_buckets)
        for i, c in self._pos.items():
            d = c - older._pos.get(i, 0)
            if d > 0:
                out._pos[i] = d
        for i, c in self._neg.items():
            d = c - older._neg.get(i, 0)
            if d > 0:
                out._neg[i] = d
        out._zero = max(0, self._zero - older._zero)
        out._n = (sum(out._pos.values()) + sum(out._neg.values())
                  + out._zero)
        out._sum = self._sum - older._sum
        if out._n:
            lows = [-out._bucket_value(max(out._neg))] if out._neg \
                else ([0.0] if out._zero else
                      [out._bucket_value(min(out._pos))])
            highs = [out._bucket_value(max(out._pos))] if out._pos \
                else ([0.0] if out._zero else
                      [-out._bucket_value(min(out._neg))])
            out._min = min(lows)
            out._max = max(highs)
        return out

    def copy(self) -> "QuantileSketch":
        out = QuantileSketch(self.rel_err, self.max_buckets)
        out.merge(self)
        return out

    # -- serialization (JSON-pure: the telemetry frame payload) ------------
    def to_dict(self) -> dict:
        return {"rel_err": self.rel_err, "n": self._n,
                "sum": self._sum,
                "min": None if self._n == 0 else self._min,
                "max": None if self._n == 0 else self._max,
                "zero": self._zero, "collapsed": self.collapsed,
                "pos": {str(i): c for i, c in self._pos.items()},
                "neg": {str(i): c for i, c in self._neg.items()}}

    @classmethod
    def from_dict(cls, d: dict,
                  max_buckets: int = 2048) -> "QuantileSketch":
        """Inverse of ``to_dict``. Raises (ValueError/KeyError/
        TypeError) on a malformed document — a torn frame must be
        COUNTED by the caller, never guessed into a sketch."""
        out = cls(float(d["rel_err"]), max_buckets)
        out._pos = {int(i): int(c) for i, c in
                    (d.get("pos") or {}).items()}
        out._neg = {int(i): int(c) for i, c in
                    (d.get("neg") or {}).items()}
        out._zero = int(d.get("zero", 0))
        out._n = int(d["n"])
        out._sum = float(d["sum"])
        out.collapsed = int(d.get("collapsed", 0))
        if any(c < 0 for c in out._pos.values()) or \
                any(c < 0 for c in out._neg.values()) or \
                out._zero < 0 or out._n < 0:
            raise ValueError("negative sketch bucket count")
        bucketed = (sum(out._pos.values()) + sum(out._neg.values())
                    + out._zero)
        if bucketed != out._n:
            raise ValueError(
                f"sketch bucket counts {bucketed} != n {out._n}")
        if out._n:
            if d.get("min") is None or d.get("max") is None:
                raise ValueError("non-empty sketch without min/max")
            out._min = float(d["min"])
            out._max = float(d["max"])
        return out
