"""Cross-host request tracing primitives (ISSUE 14 tentpole).

The observability stack before this module was strictly rank-local: a
request that prefills on rank 0 and decodes on rank 1 had its
lifecycle torn across two event rings, and ``serving/disagg.py``
refused cross-host clock deltas outright (the decode-side TTFT was
suppressed as a bogus ~0 ms same-host pair). This module supplies the
three pieces that make a cross-host delta *meaningful*:

- **Deterministic trace ids** (:func:`trace_id`): every request of a
  disaggregated mesh carries ``g<gid>`` derived from its global
  submission sequence — identical on every rank by the SPMD driver
  contract, so the prefill rank's events and the decode rank's events
  join one trace without any coordination. The serving engine stamps
  the id as a ``trace`` attr on every lifecycle event it emits for the
  request (``profiler/events.py``), and the handoff payload carries it
  across the channel.

- **A wall-clock anchor per process** (:func:`walltime`): events are
  timestamped with ``perf_counter_ns`` (process-monotonic — the right
  clock for same-host math, meaningless across hosts). Each sink flush
  stamps an ``(wall_s, t_ns)`` pair read back-to-back, so an offline
  consumer can place any event on this rank's wall clock. ``walltime``
  also honors an injected per-rank test skew (``PADDLE_CLOCK_SKEW`` =
  ``"<rank>:<seconds>[,<rank>:<seconds>]"``) so the chaos/mesh tests
  can *prove* the offset correction recovers a known skew instead of
  asserting 0 == 0 on a single-node mesh.

- **Clock alignment with an honest error bar** (:class:`ClockSync`): a
  Cristian-style ping exchange over a shared directory (the same
  substrate as the consensus board and the handoff channel). A
  non-reference rank stamps ``t0`` (its clock), pings, the reference
  rank replies with its own wall time ``t_ref``, the client stamps
  ``t1``; the sample estimates ``offset = (t0 + t1) / 2 - t_ref`` with
  uncertainty ``(t1 - t0) / 2`` — the reply is *somewhere* inside the
  round trip, and half the round trip is the tightest bound that
  requires no symmetry assumption. The best (min-uncertainty) of
  ``n_samples`` wins. Merged cross-host deltas carry that uncertainty
  instead of pretending to nanosecond truth: a TTFT measured across
  the handoff is reported as ``value ± (unc_src + unc_dst)``.

The agreed mesh-wide offset table (every rank's ``offset_s``/``unc_s``
relative to the reference rank) is published on the consensus board by
``serving/disagg.py`` and mirrored into this module's process-global
**clock state** (:func:`set_clock_state` / :func:`clock_state`), which
the metrics sink stamps into every flush line and the flight recorder
stamps into every post-mortem dump — so the offline merger
(``tools/merge_traces.py``) finds everything it needs inside the
per-rank sink artifacts alone.

Sign convention (used everywhere): ``offset_s`` of rank K is K's wall
clock MINUS the reference rank's; converting a K-stamped wall time
into reference time is ``w_ref = w_k - offset_s``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "trace_id", "walltime", "local_skew_s",
    "clock_state", "set_clock_state", "reset_clock_state",
    "ClockSync",
]

#: injected test skew: "<rank>:<seconds>[,<rank>:<seconds>]" or a bare
#: float applied to every rank (single-process tests)
SKEW_ENV = "PADDLE_CLOCK_SKEW"


def trace_id(gid: int) -> str:
    """Deterministic trace id of global request ``gid`` — the same
    string on every rank of the mesh, with no coordination."""
    return f"g{int(gid):08d}"


def _env_rank() -> int:
    """This process's mesh rank from the PADDLE_* env protocol (the
    one tools/mp_mesh.py workers always carry), without touching jax —
    skew parsing must be import-safe anywhere."""
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def local_skew_s(rank: Optional[int] = None) -> float:
    """The injected wall-clock skew of ``rank`` (default: this
    process), parsed from ``PADDLE_CLOCK_SKEW``. 0.0 when unset — the
    production value; the env knob exists so mesh tests can give one
    rank a known-wrong clock and assert the sync recovers it."""
    raw = os.environ.get(SKEW_ENV)
    if not raw:
        return 0.0
    r = _env_rank() if rank is None else int(rank)
    try:
        if ":" not in raw:
            return float(raw)
        for part in raw.split(","):
            rr, ss = part.split(":")
            if int(rr) == r:
                return float(ss)
        return 0.0
    except ValueError:
        return 0.0


def walltime(skew_s: Optional[float] = None) -> float:
    """This rank's wall clock: ``time.time()`` plus the injected test
    skew. EVERY wall stamp that participates in cross-host math (sink
    anchors, handoff trace contexts, TTFT endpoints) must come from
    here, so an injected skew is consistent — and therefore
    correctable — across all of them."""
    return time.time() + (local_skew_s() if skew_s is None else skew_s)


# ---------------------------------------------------------------------------
# process-global clock state (what the sink + flight recorder stamp)
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_state: Dict[str, object] = {
    "offset_s": None,   # this rank's wall clock minus the reference's
    "unc_s": None,      # +- bound on offset_s (half best round trip)
    "ref": 0,           # reference rank the offsets are relative to
    "synced": False,    # True once an agreed estimate was adopted
}


def set_clock_state(offset_s: Optional[float], unc_s: Optional[float],
                    ref: int = 0, synced: bool = True) -> None:
    """Adopt this rank's agreed clock offset (serving/disagg.py calls
    this when the mesh's ``clock`` consensus round publishes). The sink
    stamps the state into every subsequent flush line."""
    with _lock:
        _state["offset_s"] = None if offset_s is None else float(offset_s)
        _state["unc_s"] = None if unc_s is None else float(unc_s)
        _state["ref"] = int(ref)
        _state["synced"] = bool(synced)


def clock_state() -> dict:
    """A copy of the current clock state ({offset_s, unc_s, ref,
    synced}). ``offset_s is None`` means this rank never synced —
    consumers must treat its cross-host deltas as unbounded, not as
    exact."""
    with _lock:
        return dict(_state)


def reset_clock_state() -> None:
    set_clock_state(None, None, ref=0, synced=False)


# ---------------------------------------------------------------------------
# Cristian-style clock sync over a shared directory
# ---------------------------------------------------------------------------
class ClockSync:
    """One rank's half of the ping exchange (module docstring).

    The reference rank answers pings (``step()`` is its serve loop and
    returns True immediately — its own offset is 0 ± 0 by definition);
    every other rank issues ``n_samples`` pings, one at a time, and
    keeps the minimum-uncertainty sample. ``step()`` is non-blocking
    and cheap (one listdir / one stat), built to ride a scheduler
    heartbeat; ``estimate()`` returns ``(offset_s, unc_s)`` once ready.

    Files (all atomic tmp+rename; a rank killed mid-write leaves only
    an ignorable ``.tmp``): ``ping.<rank>.<seq>`` client -> reference,
    ``pong.<rank>.<seq>`` reference -> client (JSON ``{"t_ref": ...}``).
    Consumed files are unlinked by their reader, so the directory
    stays O(in-flight), not O(history).
    """

    def __init__(self, directory: str, rank: int, world: int, *,
                 ref: int = 0, n_samples: int = 5,
                 skew_s: Optional[float] = None):
        if world < 1 or not 0 <= rank < world:
            raise ValueError(f"bad rank/world {rank}/{world}")
        if not 0 <= ref < world:
            raise ValueError(f"reference rank {ref} outside the mesh")
        if n_samples < 1:
            raise ValueError("n_samples must be >= 1")
        self.dir = directory
        self.rank = int(rank)
        self.world = int(world)
        self.ref = int(ref)
        self.n_samples = int(n_samples)
        #: injected test skew (None -> the PADDLE_CLOCK_SKEW default);
        #: must match the skew of every other wall stamp this rank
        #: makes, or the "correction" would un-correct real stamps
        self.skew_s = local_skew_s(rank) if skew_s is None \
            else float(skew_s)
        self._seq = 0
        self._t0: Optional[float] = None      # outstanding ping stamp
        self._samples: list = []              # (unc_s, offset_s)
        os.makedirs(directory, exist_ok=True)
        # purge THIS rank's leftovers from a previous incarnation
        # (restart after a mid-sync crash): seq restarts at 0, and a
        # stale pong.<rank>.0 answered minutes ago would pair with a
        # fresh ping into a wildly-wrong offset whose tiny claimed
        # uncertainty WINS the min-unc selection. Peers' files are
        # not ours to touch.
        self._purge_own_files()

    def _purge_own_files(self) -> None:
        """Unlink THIS rank's ping/pong files (init and resync both
        need it — a stale pong pairing with a fresh ping is the
        hazard in both lifecycles)."""
        try:
            for n in os.listdir(self.dir):
                if n.startswith((f"ping.{self.rank}.",
                                 f"pong.{self.rank}.")):
                    try:
                        os.unlink(os.path.join(self.dir, n))
                    except OSError:  # pragma: no cover
                        pass
        except OSError:  # pragma: no cover - dir vanished
            pass

    # -- clock under test ---------------------------------------------------
    def _now(self) -> float:
        return walltime(self.skew_s)

    # -- protocol -----------------------------------------------------------
    def _write_atomic(self, path: str, doc: dict) -> None:
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def _serve(self) -> None:
        """Reference side: answer every outstanding ping."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for n in names:
            if not n.startswith("ping.") or ".tmp" in n:
                continue
            pong = os.path.join(self.dir, "pong." + n[len("ping."):])
            self._write_atomic(pong, {"t_ref": self._now()})
            try:
                os.unlink(os.path.join(self.dir, n))
            except OSError:  # pragma: no cover - racing second server
                pass

    def step(self) -> bool:
        """Pump the exchange; True once :meth:`estimate` is ready. The
        reference rank serves pongs and is always ready. Call from the
        scheduler heartbeat until ready (the reference keeps calling —
        peers may still be sampling)."""
        if self.rank == self.ref:
            self._serve()
            return True
        if self._t0 is not None:
            pong = os.path.join(self.dir,
                                f"pong.{self.rank}.{self._seq}")
            try:
                with open(pong) as f:
                    t_ref = float(json.load(f)["t_ref"])
            except (OSError, ValueError, KeyError):
                return self.ready          # reply not landed yet
            t1 = self._now()
            t0 = self._t0
            self._t0 = None
            self._seq += 1
            try:
                os.unlink(pong)
            except OSError:  # pragma: no cover
                pass
            self._samples.append(((t1 - t0) / 2.0,
                                  (t0 + t1) / 2.0 - t_ref))
            return self.ready
        if len(self._samples) < self.n_samples:
            ping = os.path.join(self.dir,
                                f"ping.{self.rank}.{self._seq}")
            # t0 BEFORE the write becomes visible: the reference may
            # reply the instant the rename lands, and a t_ref outside
            # [t0, t1] would break the "reply is inside the round
            # trip" premise the ± bound rests on. Stamping early only
            # WIDENS the bound — conservative by construction.
            self._t0 = self._now()
            self._write_atomic(ping, {"rank": self.rank})
        return self.ready

    def resync(self) -> None:
        """Begin a FRESH sampling round (periodic drift tracking,
        ISSUE 15): drop the previous round's samples, advance the
        sequence past any in-flight exchange and purge this rank's
        leftover ping/pong files — the same stale-pong hazard the
        ``__init__`` purge guards against, now mid-life (a pong
        answered before the resync pairing with a post-resync ping
        would claim a tiny uncertainty for a stale offset and WIN the
        min-unc selection). ``ready`` goes False until ``n_samples``
        new round trips land; the reference rank has nothing to
        resample (its offset is 0 by definition) and no-ops."""
        if self.rank == self.ref:
            return
        self._t0 = None
        self._samples = []
        self._seq += 1
        self._purge_own_files()

    @property
    def ready(self) -> bool:
        return self.rank == self.ref or \
            len(self._samples) >= self.n_samples

    def estimate(self) -> Optional[Tuple[float, float]]:
        """(offset_s, unc_s) — this rank's clock minus the reference's
        with its ± bound — or None while still sampling. The reference
        rank is exactly (0, 0): offsets are *defined* relative to it."""
        if self.rank == self.ref:
            return (0.0, 0.0)
        if not self._samples:
            return None
        unc, off = min(self._samples)
        return (off, unc)
