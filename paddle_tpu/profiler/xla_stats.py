"""Compiled-program accounting: a per-process program inventory.

The recompile telemetry (recompile.py) says WHEN a dispatch site
traced; this module says WHAT each site's compiled program costs:
compile wall-time, and XLA's own ``compiled.cost_analysis()`` FLOPs /
bytes-accessed. The inventory is keyed by the same site names the
recompile watcher uses (``serving.tick#0``, ``hybrid.step#1``, ...),
so "which program", "how often traced" and "what it costs" join on one
key. This is the harness ROADMAP items 2/3 need: a kernel or
quantization experiment's before/after is attributable per compiled
program, not inferred from whole-run wall clock.

Callers hold a ``jax.stages.Lowered`` (``jitted.lower(*avals)`` —
ShapeDtypeStructs are enough, nothing materializes):

    stats = xla_stats.record_lowered("serving.tick#0", lowered)

``record_lowered`` times the ``compile()`` call (honest wall-time of
THIS compilation — on a warm XLA process-level cache it measures the
cache hit, which is the cost the caller actually paid) and folds the
cost analysis into the registry as ``xla/<site>/compile_ms`` /
``.../flops`` / ``.../bytes_accessed`` gauges plus the inventory.

CPU caveat (documented, not hidden): the CPU backend's cost analysis
reports ``flops``/``bytes accessed`` from the optimized HLO but no
per-op timing model; on some backends/versions ``cost_analysis()``
raises — recorded as ``cost_available: False`` with compile time
still kept. Accounting never raises into the caller's hot path.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, Optional, Set

from . import recompile as _recompile
from .metrics import registry

__all__ = ["ProgramStats", "record_lowered", "record_compiled",
           "normalize_cost", "inventory", "program_inventory", "get",
           "reset", "category_breakdown", "module_sites",
           "ambiguous_modules", "register_module_site"]


class ProgramStats:
    """One dispatch site's compiled-program record.

    Beyond the cost-analysis totals it carries (when the compiled HLO
    text was analyzable): the HLO **module name** (the join key parsed
    device traces report as ``args.hlo_module`` — device_trace.py
    correlates slices back to sites through it), a **per-op-category
    FLOPs/bytes breakdown** (``categories``: matmul / attention /
    scatter-gather / elementwise / collective, derived from the
    optimized HLO's entry computation — the same categories traced
    time is bucketed into, so modeled cost and measured microseconds
    join on one axis), and **per-collective-kind byte counts**
    (``collectives``: result-buffer bytes per execution by kind, the
    instrument.collective_stats convention)."""

    __slots__ = ("site", "compile_ms", "flops", "bytes_accessed",
                 "cost", "recorded_unix", "module", "categories",
                 "collectives", "flops_unattributed")

    def __init__(self, site: str, compile_ms: Optional[float],
                 flops: Optional[float], bytes_accessed: Optional[float],
                 cost: dict, module: Optional[str] = None,
                 categories: Optional[dict] = None,
                 collectives: Optional[dict] = None,
                 flops_unattributed: Optional[float] = None):
        self.site = site
        self.compile_ms = compile_ms
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.cost = cost
        self.module = module
        self.categories = categories or {}
        self.collectives = collectives or {}
        self.flops_unattributed = flops_unattributed
        self.recorded_unix = time.time()

    def to_dict(self) -> dict:
        return {
            "site": self.site,
            "compile_ms": None if self.compile_ms is None
            else round(self.compile_ms, 3),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "cost_available": bool(self.cost),
            "module": self.module,
            "categories": self.categories,
            "collectives": self.collectives,
            "flops_unattributed": self.flops_unattributed,
        }


_lock = threading.Lock()
_programs: Dict[str, ProgramStats] = {}
#: HLO module name -> dispatch site (the trace-slice join key); a
#: module name claimed by TWO different sites (two jits of same-named
#: functions) lands in _ambiguous — correlation stays possible but is
#: flagged.
_module_sites: Dict[str, str] = {}
_ambiguous: Set[str] = set()

_HLO_MODULE_RE = re.compile(r"^HloModule ([^,\s]+)", re.M)
_MLIR_MODULE_RE = re.compile(r"^module @([^\s(]+)", re.M)


def register_module_site(module: str, site: str) -> None:
    """Register (or re-register) the HLO-module-name -> site mapping
    device_trace uses to attribute parsed slices."""
    with _lock:
        prior = _module_sites.get(module)
        if prior is not None and prior != site:
            _ambiguous.add(module)
        _module_sites[module] = site


def module_sites() -> Dict[str, str]:
    with _lock:
        return dict(_module_sites)


def ambiguous_modules() -> Set[str]:
    with _lock:
        return set(_ambiguous)


# ---------------------------------------------------------------------------
# per-op-category breakdown of one compiled program's HLO text
# ---------------------------------------------------------------------------
# one scheduled instruction: `%name = type op(...)` — type is either a
# single `f32[64,48]{1,0}` or a tuple `(f32[..], s32[..])`
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\(?[a-z][a-z0-9]+\[[^=]*?)\s"
    r"([a-z][a-z0-9\-]*)\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]+)\[([0-9,]*)\]")
_DOT_LHS_RE = re.compile(r"\(([a-z][a-z0-9]+)\[([0-9,]*)\]")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=([a-z0-9?]+)_([a-z0-9?]+)->")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id"})


def _dims(dim_str: str) -> list:
    return [int(d) for d in dim_str.split(",") if d]


def _result_bytes(type_str: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in _dims(dims):
            n *= d
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def _dot_flops(line: str, result_type: str) -> Optional[float]:
    """2 * prod(result dims) * prod(lhs contracting dims) — exact for
    dot_general including batch dims (both live in the result)."""
    lhs = _DOT_LHS_RE.search(line[line.index("dot("):])
    cm = _CONTRACT_RE.search(line)
    rm = _SHAPE_RE.search(result_type)
    if not (lhs and cm and rm):
        return None
    lhs_dims = _dims(lhs.group(2))
    contract = 1
    for i in _dims(cm.group(1)):
        if i >= len(lhs_dims):
            return None
        contract *= lhs_dims[i]
    result = 1
    for d in _dims(rm.group(2)):
        result *= d
    return 2.0 * result * contract


def _conv_flops(line: str, result_type: str) -> Optional[float]:
    """2 * prod(result) * (kernel elements / output channels): each
    output point multiplies the whole kernel volume for its channel.
    Output-channel position parsed from dim_labels' rhs spec ('o')."""
    idx = line.find("convolution(")
    if idx < 0:
        return None
    operands = _DOT_LHS_RE.findall(line[idx:])
    dl = _DIM_LABELS_RE.search(line)
    rm = _SHAPE_RE.search(result_type)
    if len(operands) < 2 or not dl or not rm:
        return None
    rhs_dims = _dims(operands[1][1])
    rhs_spec = dl.group(2)
    if "o" not in rhs_spec or len(rhs_spec) != len(rhs_dims):
        return None
    out_ch = rhs_dims[rhs_spec.index("o")]
    kernel = 1
    for d in rhs_dims:
        kernel *= d
    result = 1
    for d in _dims(rm.group(2)):
        result *= d
    return 2.0 * result * (kernel / max(out_ch, 1))


def category_breakdown(hlo_text: str,
                       total_flops: Optional[float] = None) -> dict:
    """Per-op-category FLOPs/bytes breakdown of ONE compiled program's
    optimized-HLO text — the modeled counterpart of device_trace's
    per-category measured time, on the same category axis.

    Bytes are result-buffer sizes of the ENTRY computation's scheduled
    instructions (each is one thunk/slice in a device trace — counting
    fusion bodies too would double-count); per the collective_stats
    convention these are per-execution buffer bytes, not wire bytes.
    FLOPs are computed analytically for every ``dot`` / ``convolution``
    in ANY computation (fusions can swallow them) and attributed to
    matmul; the remainder against ``total_flops`` (cost_analysis's own
    number, when given) is returned as ``flops_unattributed`` so the
    totals still reconcile. Categories: matmul / attention /
    scatter-gather / elementwise / collective.

    Returns ``{"categories": {cat: {ops, bytes[, flops]}},
    "flops_unattributed": float | None}`` — the reconciliation number
    sits NEXT TO the homogeneous per-category table, never inside it.
    """
    from .device_trace import categorize_op

    cats: Dict[str, dict] = {}
    in_entry = False
    matmul_flops = 0.0
    flops_known = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if line.startswith("}"):
            in_entry = False
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rtype, op = m.groups()
        if op in _SKIP_OPS:
            continue
        if op == "dot" or op == "convolution":
            f = _dot_flops(line, rtype) if op == "dot" \
                else _conv_flops(line, rtype)
            if f is not None:
                matmul_flops += f
                flops_known = True
        if not in_entry:
            continue
        cat = categorize_op(f"{name} {op}")
        c = cats.setdefault(cat, {"ops": 0, "bytes": 0})
        c["ops"] += 1
        c["bytes"] += _result_bytes(rtype)
    if flops_known:
        cats.setdefault("matmul", {"ops": 0, "bytes": 0})
        cats["matmul"]["flops"] = matmul_flops
    return {"categories": dict(sorted(cats.items())),
            "flops_unattributed":
            max(total_flops - matmul_flops, 0.0)
            if total_flops is not None and flops_known else None}


def normalize_cost(ca) -> dict:
    """``cost_analysis()`` returns a list of per-device dicts on some
    jax versions, a dict on others, None on backends without it — one
    plain dict out (empty when unavailable)."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else {}


def record_compiled(site: str, compiled,
                    compile_s: Optional[float] = None) -> ProgramStats:
    """Fold an already-compiled program's cost analysis (and, when the
    caller timed it, the compile wall-time) into the inventory +
    registry. Also analyzes the compiled HLO text (best-effort): the
    module name registers the trace-slice join key
    (``register_module_site``), and the per-op-category +
    per-collective breakdowns ride on the ProgramStats. Text analysis
    is skipped silently where ``as_text()`` is unavailable — the
    totals still land."""
    try:
        cost = normalize_cost(compiled.cost_analysis())
    except Exception:
        cost = {}
    flops = cost.get("flops")
    byts = cost.get("bytes accessed")
    module = categories = collectives = unattrib = None
    try:
        text = compiled.as_text()
        m = _HLO_MODULE_RE.search(text) or _MLIR_MODULE_RE.search(text)
        if m:
            module = m.group(1)
            register_module_site(module, site)
        bd = category_breakdown(
            text, None if flops is None else float(flops))
        categories = bd["categories"]
        unattrib = bd["flops_unattributed"]
        from .instrument import collective_stats

        cs = collective_stats(text)
        collectives = {op: {"ops": n, "bytes": cs["bytes"].get(op, 0)}
                       for op, n in cs["ops"].items()}
    except Exception:
        pass
    stats = ProgramStats(site, None if compile_s is None
                         else compile_s * 1e3,
                         None if flops is None else float(flops),
                         None if byts is None else float(byts), cost,
                         module=module, categories=categories,
                         collectives=collectives,
                         flops_unattributed=unattrib)
    with _lock:
        _programs[site] = stats
    reg = registry()
    if stats.compile_ms is not None:
        reg.gauge(f"xla/{site}/compile_ms").set(stats.compile_ms)
    if stats.flops is not None:
        reg.gauge(f"xla/{site}/flops").set(stats.flops)
    if stats.bytes_accessed is not None:
        reg.gauge(f"xla/{site}/bytes_accessed").set(stats.bytes_accessed)
    reg.counter("xla/programs_recorded").add(1)
    return stats


def record_lowered(site: str, lowered) -> ProgramStats:
    """Compile ``lowered`` (timed — the recorded compile wall-time)
    and record its cost analysis. The compile runs suppressed: it is a
    diagnostic lowering by design, not a silent recompile."""
    with _recompile.suppressed():
        t0 = time.perf_counter()
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
    return record_compiled(site, compiled, compile_s=dt)


def get(site: str) -> Optional[ProgramStats]:
    with _lock:
        return _programs.get(site)


def inventory() -> Dict[str, dict]:
    """JSON-ready {site: stats} — what bench blocks and the sink
    embed."""
    with _lock:
        return {site: s.to_dict() for site, s in sorted(_programs.items())}


#: package-level spelling (``profiler.program_inventory()``) — the
#: module-local name stays the short one
program_inventory = inventory


def reset() -> None:
    """Clear the inventory AND the module->site join maps: a stale
    mapping would attribute trace slices to a site the (cleared)
    inventory no longer holds, and a prior engine generation's
    registration would permanently flag a re-used module name
    ambiguous. The contract stays: record programs (again) before
    capturing a trace window."""
    with _lock:
        _programs.clear()
        _module_sites.clear()
        _ambiguous.clear()
