"""Compiled-program accounting: a per-process program inventory.

The recompile telemetry (recompile.py) says WHEN a dispatch site
traced; this module says WHAT each site's compiled program costs:
compile wall-time, and XLA's own ``compiled.cost_analysis()`` FLOPs /
bytes-accessed. The inventory is keyed by the same site names the
recompile watcher uses (``serving.tick#0``, ``hybrid.step#1``, ...),
so "which program", "how often traced" and "what it costs" join on one
key. This is the harness ROADMAP items 2/3 need: a kernel or
quantization experiment's before/after is attributable per compiled
program, not inferred from whole-run wall clock.

Callers hold a ``jax.stages.Lowered`` (``jitted.lower(*avals)`` —
ShapeDtypeStructs are enough, nothing materializes):

    stats = xla_stats.record_lowered("serving.tick#0", lowered)

``record_lowered`` times the ``compile()`` call (honest wall-time of
THIS compilation — on a warm XLA process-level cache it measures the
cache hit, which is the cost the caller actually paid) and folds the
cost analysis into the registry as ``xla/<site>/compile_ms`` /
``.../flops`` / ``.../bytes_accessed`` gauges plus the inventory.

CPU caveat (documented, not hidden): the CPU backend's cost analysis
reports ``flops``/``bytes accessed`` from the optimized HLO but no
per-op timing model; on some backends/versions ``cost_analysis()``
raises — recorded as ``cost_available: False`` with compile time
still kept. Accounting never raises into the caller's hot path.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import recompile as _recompile
from .metrics import registry

__all__ = ["ProgramStats", "record_lowered", "record_compiled",
           "normalize_cost", "inventory", "program_inventory", "get",
           "reset"]


class ProgramStats:
    """One dispatch site's compiled-program record."""

    __slots__ = ("site", "compile_ms", "flops", "bytes_accessed",
                 "cost", "recorded_unix")

    def __init__(self, site: str, compile_ms: Optional[float],
                 flops: Optional[float], bytes_accessed: Optional[float],
                 cost: dict):
        self.site = site
        self.compile_ms = compile_ms
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.cost = cost
        self.recorded_unix = time.time()

    def to_dict(self) -> dict:
        return {
            "site": self.site,
            "compile_ms": None if self.compile_ms is None
            else round(self.compile_ms, 3),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "cost_available": bool(self.cost),
        }


_lock = threading.Lock()
_programs: Dict[str, ProgramStats] = {}


def normalize_cost(ca) -> dict:
    """``cost_analysis()`` returns a list of per-device dicts on some
    jax versions, a dict on others, None on backends without it — one
    plain dict out (empty when unavailable)."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else {}


def record_compiled(site: str, compiled,
                    compile_s: Optional[float] = None) -> ProgramStats:
    """Fold an already-compiled program's cost analysis (and, when the
    caller timed it, the compile wall-time) into the inventory +
    registry."""
    try:
        cost = normalize_cost(compiled.cost_analysis())
    except Exception:
        cost = {}
    flops = cost.get("flops")
    byts = cost.get("bytes accessed")
    stats = ProgramStats(site, None if compile_s is None
                         else compile_s * 1e3,
                         None if flops is None else float(flops),
                         None if byts is None else float(byts), cost)
    with _lock:
        _programs[site] = stats
    reg = registry()
    if stats.compile_ms is not None:
        reg.gauge(f"xla/{site}/compile_ms").set(stats.compile_ms)
    if stats.flops is not None:
        reg.gauge(f"xla/{site}/flops").set(stats.flops)
    if stats.bytes_accessed is not None:
        reg.gauge(f"xla/{site}/bytes_accessed").set(stats.bytes_accessed)
    reg.counter("xla/programs_recorded").add(1)
    return stats


def record_lowered(site: str, lowered) -> ProgramStats:
    """Compile ``lowered`` (timed — the recorded compile wall-time)
    and record its cost analysis. The compile runs suppressed: it is a
    diagnostic lowering by design, not a silent recompile."""
    with _recompile.suppressed():
        t0 = time.perf_counter()
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
    return record_compiled(site, compiled, compile_s=dt)


def get(site: str) -> Optional[ProgramStats]:
    with _lock:
        return _programs.get(site)


def inventory() -> Dict[str, dict]:
    """JSON-ready {site: stats} — what bench blocks and the sink
    embed."""
    with _lock:
        return {site: s.to_dict() for site, s in sorted(_programs.items())}


#: package-level spelling (``profiler.program_inventory()``) — the
#: module-local name stays the short one
program_inventory = inventory


def reset() -> None:
    with _lock:
        _programs.clear()
