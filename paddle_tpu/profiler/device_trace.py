"""Device-time truth: parsed XLA trace windows (ISSUE 11).

Everything device-side the profiler reported before this module was an
attribution or a model: ``phase/comm_measured_ms`` is step wall time
apportioned by cost-analysis bytes (truthful about magnitude, silent
about overlap), per-op "timings" were named-scope metadata. This module
is the measurement: wrap a window of hot-loop iterations in
``jax.profiler.trace`` and parse the trace-event JSON the profiler
exports (``plugins/profile/<run>/*.trace.json.gz``) with **stdlib
only** — gzip + json, no tensorboard/tensorflow dependency. From the
parsed timeline it derives, per capture window:

- **device-busy wall time** (interval union of device-op slices) and
  the host-gap split: ``wall = device_busy + host_gap`` — the measured
  version of PR 3's dispatch-vs-execution gap;
- a **per-op-category breakdown** (matmul / attention / scatter-gather
  / elementwise / collective) by slice count and microseconds;
- **per-collective measured durations by kind** (all_reduce /
  all_gather / reduce_scatter / ppermute / all_to_all), joined against
  the per-site collective BYTE accounting xla_stats already keeps — so
  bytes and microseconds finally sit in one record;
- a measured **compute∩comm overlap fraction**: |union(collective
  slices) ∩ union(non-collective device slices)| / |union(collective
  slices)| — in [0, 1], 0 when nothing overlapped (or no collectives
  ran), 1 when every collective microsecond had compute in flight.
  This upgrades ``phase/comm_measured_ms`` (apportioned) with
  ``phase/comm_traced_ms`` (measured; the old gauge is kept for
  comparison);
- a **goodput/MFU ledger**: cost-analysis model FLOPs (xla_stats) ×
  traced executions ÷ measured wall time vs the device's peak, plus
  ``goodput_busy_frac`` (device-busy share of wall — the fraction of
  the window the device was doing anything at all).

**Site correlation.** Trace slices carry ``args.hlo_module``
(``jit_step``, ``jit_tick``, ...). ``xla_stats.record_lowered`` /
``record_compiled`` register each recorded program's HLO module name
next to its dispatch-site name (``hybrid.step#0``,
``serving.tick#1``), so parsed slices join the program inventory —
and its FLOPs/bytes/collective-bytes — on the site key the rest of the
profiler already uses. Record programs (``record_program_stats()`` /
``profile_step_phases``) BEFORE capturing, or modules land in
``unattributed_modules``. Two live programs lowered from same-named
functions share a module name; such rows are flagged ``ambiguous``.

**Per-site executions** are estimated from the trace itself: the
minimum per-op-name slice count inside a module (ops inside compiled
loops repeat per iteration; top-level ops run exactly once per
execution, so the minimum is the execution count). The capture's
``steps`` hint (iterations the caller wrapped) rides alongside.

**CPU semantics (honest).** On the CPU backend the "device" slices are
XLA:CPU **thunks** executed on host threads (``args.hlo_op`` on the
thunk-executor thread) — real measured per-op wall time of the
compiled program, but host-scheduled: overlap is ~0 by construction
and the busy union measures the thunk executor, not an accelerator.
On TPU the same parser reads the device-stream slices. Every parser
path is exercised by checked-in fixture tests on any backend.

**Peak FLOPs** for MFU: TPU generations get their bf16 peak; CPU gets
a one-shot MEASURED matmul calibration at the first capture (source
``"calibrated"`` — ISSUE 16 satellite, retiring the nominal
placeholder), falling back to the labeled nominal
``_PEAK_FLOPS["cpu"]`` only if the measurement itself fails —
``peak_flops_source`` says which one was used; pass ``peak_flops=``
or set ``PADDLE_PEAK_FLOPS`` to override (the env var always wins).

Entry points::

    with device_trace.capture(steps=4, label="hybrid.step") as cap:
        for _ in range(4): step()
    cap.summary                      # the parsed window

    win = device_trace.TraceWindow(length=2, every=100, start=10)
    for i in range(n_steps):
        with win.step():
            trainer.step(batch)      # steps 10-11, 110-111, ... traced
    win.last                         # newest summary

Wired through: ``profile_step_phases(trace_window=k)`` (hybrid +
strategy_compiler), ``ServingEngine.trace_window()``, ``serve_bench
--trace-window N`` / ``bench.py`` profiler blocks. Each summary is
folded into registry gauges (``phase/comm_traced_ms``,
``phase/comm_overlap_frac``, ``trace/*``), persisted by an active sink
as ``trace_summary.json`` (schema-checked in CI), and attached to
flight-recorder dumps.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from .metrics import registry

__all__ = [
    "TraceParseError", "capture", "trace_capture", "TraceWindow",
    "find_trace_file", "load_trace_events", "parse_timeline",
    "summarize", "record_summary", "last_summary",
    "last_trace_summary", "categorize_op", "collective_kind",
    "overlap_fraction", "interval_union_ms", "default_peak_flops",
]


class TraceParseError(ValueError):
    """A trace file that cannot be read as trace-event JSON: truncated
    gzip, malformed JSON, or a document without ``traceEvents``."""


# ---------------------------------------------------------------------------
# op classification
# ---------------------------------------------------------------------------
#: substring -> collective kind, checked in order (reduce_scatter before
#: all_reduce is irrelevant — the spellings are disjoint; both the HLO
#: dash form and the StableHLO underscore form are matched, and async
#: -start/-done slices classify to the same kind)
_COLLECTIVE_KINDS = (
    ("all-reduce", "all_reduce"), ("all_reduce", "all_reduce"),
    ("reduce-scatter", "reduce_scatter"),
    ("reduce_scatter", "reduce_scatter"),
    ("all-gather", "all_gather"), ("all_gather", "all_gather"),
    ("all-to-all", "all_to_all"), ("all_to_all", "all_to_all"),
    ("collective-permute", "ppermute"),
    ("collective_permute", "ppermute"), ("ppermute", "ppermute"),
    ("collective-broadcast", "collective_broadcast"),
    ("collective_broadcast", "collective_broadcast"),
)

_MATMUL_PAT = ("dot", "conv", "einsum", "matmul", "cublas", "gemm")
_ATTENTION_PAT = ("attention", "attn", "softmax", "flash")
_SCATTER_GATHER_PAT = ("scatter", "gather", "dynamic-slice",
                       "dynamic_slice", "dynamic-update-slice",
                       "dynamic_update_slice", "sort", "take")

#: the four compute categories + collectives; sums over a summary's
#: ``categories`` cover every parsed device slice exactly once
CATEGORIES = ("matmul", "attention", "scatter-gather", "elementwise",
              "collective")


def collective_kind(name: str) -> Optional[str]:
    """Collective kind of an op/slice name, or None. Understands the
    compiled-HLO dash spelling (``all-reduce-start``), the StableHLO
    underscore spelling, and fusion names that embed either."""
    n = name.lower()
    for pat, kind in _COLLECTIVE_KINDS:
        if pat in n:
            return kind
    return None


def categorize_op(name: str) -> str:
    """Category of one device-op slice by its (HLO) name. On TPU the
    op name carries jax named-scope prefixes (``fwd/attn/dot.3``) so
    scope words like "attention" classify; on CPU the thunk name is
    the bare HLO instruction (``dot.4``, ``broadcast_maximum_fusion``)
    and classification rides the opcode embedded in it."""
    n = name.lower()
    if collective_kind(n) is not None:
        return "collective"
    if any(p in n for p in _ATTENTION_PAT):
        return "attention"
    if any(p in n for p in _MATMUL_PAT):
        return "matmul"
    if any(p in n for p in _SCATTER_GATHER_PAT):
        return "scatter-gather"
    return "elementwise"


# ---------------------------------------------------------------------------
# interval arithmetic (the overlap/busy math, unit-tested directly)
# ---------------------------------------------------------------------------
def _merge(intervals: List[Tuple[float, float]]
           ) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    ivs = sorted(intervals)
    out = [list(ivs[0])]
    for s, e in ivs[1:]:
        if s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1][1] = e
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def interval_union_ms(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length of ``[(start_us, end_us), ...]`` in ms."""
    return sum(e - s for s, e in _merge(intervals)) / 1e3


def _intersection_len_us(a: List[Tuple[float, float]],
                         b: List[Tuple[float, float]]) -> float:
    """|union(a) ∩ union(b)| in us (both merged by the caller)."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_fraction(comm: List[Tuple[float, float]],
                     compute: List[Tuple[float, float]]) -> float:
    """Fraction of collective time with compute in flight: |union(comm)
    ∩ union(compute)| / |union(comm)|, clamped to [0, 1]; 0.0 when no
    collective slices exist (nothing to overlap)."""
    cm = _merge(comm)
    denom = sum(e - s for s, e in cm)
    if denom <= 0:
        return 0.0
    frac = _intersection_len_us(cm, _merge(compute)) / denom
    return min(max(frac, 0.0), 1.0)


# ---------------------------------------------------------------------------
# trace-file loading (stdlib only)
# ---------------------------------------------------------------------------
def find_trace_file(log_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``log_dir/plugins/profile/*/``
    (the jax profiler's TensorBoard export layout); falls back to a
    ``perfetto_trace.json.gz`` (same document minus metadata) or a bare
    ``*.trace.json(.gz)`` directly under ``log_dir``."""
    pats = (os.path.join(log_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(log_dir, "plugins", "profile", "*",
                         "perfetto_trace.json.gz"),
            os.path.join(log_dir, "*.trace.json.gz"),
            os.path.join(log_dir, "*.trace.json"))
    for pat in pats:
        files = [f for f in glob.glob(pat)
                 if not os.path.basename(f).startswith("perfetto")
                 or "perfetto" in pat]
        if files:
            return max(files, key=os.path.getmtime)
    return None


def load_trace_events(path: str) -> dict:
    """Read one trace-event document ({"traceEvents": [...]} or a bare
    event list) from ``path`` (gzipped by extension). Raises
    :class:`TraceParseError` on truncated gzip / malformed JSON /
    wrong document shape — the negative paths fixture tests pin."""
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8",
                    errors="replace") as f:
            doc = json.load(f)
    except (OSError, EOFError, ValueError, UnicodeDecodeError) as e:
        # gzip truncation surfaces as EOFError, bad gzip magic as
        # OSError(BadGzipFile), malformed JSON as JSONDecodeError
        raise TraceParseError(f"{path}: {type(e).__name__}: {e}") from e
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("traceEvents"), list):
        raise TraceParseError(
            f"{path}: not a trace-event document (no traceEvents list)")
    return doc


# ---------------------------------------------------------------------------
# timeline parsing
# ---------------------------------------------------------------------------
class Timeline:
    """Parsed slices of one capture window.

    ``device_ops``: [(name, module|None, ts_us, dur_us)] — slices with
    HLO metadata (``args.hlo_op``/``hlo_module``) or sitting under a
    ``/device:*`` process (TPU streams). ``host_spans``: named host
    annotation slices (TraceAnnotations — profiler scopes — and step
    markers), runtime-internal noise filtered out. The window bounds
    (``t_min_us``/``t_max_us``) cover device ops + host annotations
    ONLY — jax's own trace-session setup/teardown slices (seconds on a
    first capture) must not count as hot-loop host gap.
    """

    __slots__ = ("device_ops", "host_spans", "events_total",
                 "t_min_us", "t_max_us")

    def __init__(self):
        self.device_ops: List[Tuple[str, Optional[str], float, float]] = []
        self.host_spans: List[Tuple[str, float, float]] = []
        self.events_total = 0
        self.t_min_us: Optional[float] = None
        self.t_max_us: Optional[float] = None


_HOST_NOISE = ("PjitFunction", "ParseArguments", "ThreadpoolListener",
               "ThunkExecutor")


def _is_host_annotation(name: str) -> bool:
    # keep profiler scopes ("hybrid/fwd", "serving/tick") and step
    # annotations; drop the python tracer ("$file:line fn") and C++
    # runtime internals ("TfrtCpuExecutable::Execute")
    if name.startswith("$") or "::" in name:
        return False
    if any(p in name for p in _HOST_NOISE):
        return False
    return "/" in name or name.startswith("train ")


def parse_timeline(doc: dict) -> Timeline:
    """Split a trace-event document into device-op slices and host
    annotation spans. Events without a duration (metadata, counters,
    instant events) only extend the window bounds."""
    tl = Timeline()
    device_pids = set()
    evs = doc.get("traceEvents", [])
    tl.events_total = len(evs)
    for e in evs:
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = (e.get("args") or {}).get("name", "")
            if isinstance(pname, str) and "/device:" in pname:
                device_pids.add(e.get("pid"))
    for e in evs:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        try:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        name = e.get("name")
        if not isinstance(name, str):
            continue
        args = e.get("args") or {}
        is_device = (isinstance(args, dict)
                     and ("hlo_op" in args or "hlo_module" in args)) \
            or e.get("pid") in device_pids
        if is_device:
            module = args.get("hlo_module") if isinstance(args, dict) \
                else None
            tl.device_ops.append((name, module, ts, dur))
        elif _is_host_annotation(name):
            tl.host_spans.append((name, ts, dur))
        else:
            continue
        if tl.t_min_us is None or ts < tl.t_min_us:
            tl.t_min_us = ts
        if tl.t_max_us is None or ts + dur > tl.t_max_us:
            tl.t_max_us = ts + dur
    return tl


# ---------------------------------------------------------------------------
# peak FLOPs (MFU denominator)
# ---------------------------------------------------------------------------
#: bf16 peak FLOP/s per chip by device-kind substring (bench.py table);
#: the CPU entry is the FALLBACK for hosts where the measured matmul
#: calibration below fails — peak_flops_source labels which one a
#: ledger actually used.
_PEAK_FLOPS = {"v6": 918e12, "v5p": 459e12, "v5": 197e12,
               "v4": 275e12, "cpu": 5e10}

#: one-shot CPU calibration cache: (peak FLOP/s or None, done flag) —
#: measured at the FIRST capture's summarize and reused for the
#: process's lifetime (a per-capture re-measure would make MFUs from
#: the same run mutually incomparable)
_cpu_calibration: Optional[float] = None
_cpu_calibrated = False
_calib_lock = threading.Lock()


def _measure_cpu_peak_flops(n: int = 512,
                            reps: int = 5) -> Optional[float]:
    """Measured f32 matmul throughput of THIS host (best of ``reps``
    timed ``n x n`` BLAS multiplies after one warmup) — the honest CPU
    MFU denominator the old nominal placeholder stood in for. Best-of
    (not mean) deliberately: the denominator should be the machine's
    demonstrated peak, so reported MFU stays <= 1 instead of drifting
    above it when a timing rep got descheduled. Returns None on any
    failure — the caller falls back to the labeled nominal value,
    never guesses."""
    try:
        import numpy as np

        rng = np.random.default_rng(0)
        a = rng.standard_normal((n, n)).astype(np.float32)
        b = rng.standard_normal((n, n)).astype(np.float32)
        a @ b                               # warm the BLAS path
        best = None
        flop = 2.0 * n ** 3
        for _ in range(reps):
            t0 = time.perf_counter()
            (a @ b).sum()                   # .sum() defeats lazy eval
            dt = time.perf_counter() - t0
            if dt > 0 and (best is None or dt < best):
                best = dt
        return None if best is None else flop / best
    except Exception:  # pragma: no cover - exotic BLAS failure
        return None


def default_peak_flops() -> Tuple[Optional[float], str]:
    """(peak FLOP/s, source label) for the local device. Precedence:
    ``PADDLE_PEAK_FLOPS`` env var, the TPU-generation table, the
    one-shot measured CPU matmul calibration (source
    ``"calibrated"``), the labeled nominal CPU fallback."""
    global _cpu_calibration, _cpu_calibrated
    env = os.environ.get("PADDLE_PEAK_FLOPS")
    if env:
        try:
            return float(env), "env:PADDLE_PEAK_FLOPS"
        except ValueError:
            pass
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "").lower()
        if dev.platform != "cpu":
            for key in ("v6", "v5p", "v5", "v4"):
                if key in kind or (key == "v5" and "lite" in kind):
                    return _PEAK_FLOPS[key], f"tpu-{key}-bf16-peak"
            return _PEAK_FLOPS["v5"], "tpu-default-v5e-bf16-peak"
    except Exception:
        pass
    with _calib_lock:
        if not _cpu_calibrated:
            _cpu_calibration = _measure_cpu_peak_flops()
            _cpu_calibrated = True
        if _cpu_calibration is not None:
            return _cpu_calibration, "calibrated"
    return _PEAK_FLOPS["cpu"], "nominal-cpu-placeholder"


# ---------------------------------------------------------------------------
# summarization
# ---------------------------------------------------------------------------
def _cat_table() -> Dict[str, dict]:
    return {c: {"count": 0, "ms": 0.0} for c in CATEGORIES}


def summarize(doc_or_timeline, steps: Optional[int] = None,
              peak_flops: Optional[float] = None,
              label: str = "trace") -> dict:
    """Derive the full device-time summary (module docstring) from a
    parsed timeline (or raw trace-event document). Pure host math —
    never dispatches device work, so it is safe on post-mortem paths.

    ``steps``: how many hot-loop iterations the capture wrapped (the
    per-step normalizations; None leaves them out). ``peak_flops``:
    MFU denominator override (default :func:`default_peak_flops`).
    """
    from . import xla_stats as _xla

    tl = doc_or_timeline if isinstance(doc_or_timeline, Timeline) \
        else parse_timeline(doc_or_timeline)
    if peak_flops is None:
        peak_flops, peak_src = default_peak_flops()
    else:
        peak_src = "caller"

    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"

    wall_ms = 0.0
    if tl.t_min_us is not None and tl.t_max_us is not None:
        wall_ms = (tl.t_max_us - tl.t_min_us) / 1e3

    categories = _cat_table()
    collectives: Dict[str, dict] = {}
    comm_iv: List[Tuple[float, float]] = []
    compute_iv: List[Tuple[float, float]] = []
    all_iv: List[Tuple[float, float]] = []
    # per-module aggregation: slices, per-op-name counts, categories
    mod_ops: Dict[Optional[str], dict] = {}
    for name, module, ts, dur in tl.device_ops:
        iv = (ts, ts + dur)
        all_iv.append(iv)
        cat = categorize_op(name)
        categories[cat]["count"] += 1
        categories[cat]["ms"] += dur / 1e3
        if cat == "collective":
            kind = collective_kind(name)
            c = collectives.setdefault(kind, {"count": 0, "ms": 0.0})
            c["count"] += 1
            c["ms"] += dur / 1e3
            comm_iv.append(iv)
        else:
            compute_iv.append(iv)
        m = mod_ops.setdefault(module, {
            "ops": 0, "device_ms": 0.0, "op_counts": {},
            "categories": _cat_table(), "collectives": {}})
        m["ops"] += 1
        m["device_ms"] += dur / 1e3
        m["op_counts"][name] = m["op_counts"].get(name, 0) + 1
        m["categories"][cat]["count"] += 1
        m["categories"][cat]["ms"] += dur / 1e3
        if cat == "collective":
            mc = m["collectives"].setdefault(
                collective_kind(name), {"count": 0, "ms": 0.0})
            mc["count"] += 1
            mc["ms"] += dur / 1e3

    device_busy_ms = interval_union_ms(all_iv)
    host_gap_ms = max(wall_ms - device_busy_ms, 0.0)
    busy_frac = device_busy_ms / wall_ms if wall_ms > 0 else 0.0
    comm_ms = sum(c["ms"] for c in collectives.values())
    comm_overlap = overlap_fraction(comm_iv, compute_iv)

    # --- site correlation + per-site ledger -------------------------------
    module_sites = _xla.module_sites()
    ambiguous = _xla.ambiguous_modules()
    inv = {s.site: s for s in map(_xla.get, _xla.inventory())
           if s is not None}
    sites: Dict[str, dict] = {}
    unattributed: Dict[str, dict] = {}
    for module, m in mod_ops.items():
        # min per-op-name count estimates executions (loop-body ops
        # repeat per iteration; unconditional top-level ops run exactly
        # once per execution) — a LOWER bound for programs with
        # lax.cond branches, whose branch-local ops skip executions
        execs = min(m["op_counts"].values()) if m["op_counts"] else 0
        site = module_sites.get(module) if module else None
        row = {
            "module": module,
            "ops": m["ops"],
            "device_ms": round(m["device_ms"], 4),
            "executions": execs,
            "executions_source": "trace_min_op_count",
            "categories": {c: {"count": v["count"],
                               "ms": round(v["ms"], 4)}
                           for c, v in m["categories"].items()
                           if v["count"]},
            "collectives": {k: {"count": v["count"],
                                "ms": round(v["ms"], 4)}
                            for k, v in m["collectives"].items()},
        }
        if site is None:
            unattributed[module or "<unknown>"] = {
                "ops": row["ops"], "device_ms": row["device_ms"],
                "executions": execs}
            continue
        if module in ambiguous:
            row["ambiguous"] = True
        sites[site] = row

    # with ONE attributed site and a steps hint, the hint is the exact
    # execution count (the caller counted its own iterations/ticks) —
    # branch-skipping can't fool it
    if steps and len(sites) == 1:
        row = next(iter(sites.values()))
        row["executions"] = int(steps)
        row["executions_source"] = "steps_hint"

    model_flops_total = 0.0
    flops_known = False
    for site, row in sites.items():
        execs = row["executions"]
        row["device_ms_per_exec"] = round(
            row["device_ms"] / execs, 4) if execs else None
        ps = inv.get(site)
        if ps is not None and ps.flops is not None and execs:
            # MODEL flops (cost analysis counts every op statically —
            # both lax.cond branches included) × traced executions: a
            # join of modeled cost onto measured time, stated as such
            row["flops_per_exec"] = ps.flops
            flops = ps.flops * execs
            model_flops_total += flops
            flops_known = True
            if row["device_ms"] > 0:
                row["model_flops_per_s"] = round(
                    flops / (row["device_ms"] / 1e3), 3)
                if peak_flops:
                    row["mfu"] = round(
                        flops / (row["device_ms"] / 1e3) / peak_flops,
                        6)
        # join: modeled collective BYTES (per execution, from the
        # program's compiled HLO) next to the traced microseconds
        if ps is not None and ps.collectives:
            for kind, cb in ps.collectives.items():
                dst = row["collectives"].setdefault(
                    kind, {"count": 0, "ms": 0.0})
                dst["bytes_per_exec"] = cb.get("bytes")
                dst["modeled_ops_per_exec"] = cb.get("ops")

    # fold per-kind modeled bytes up to the window level
    for row in sites.values():
        execs = row["executions"]
        for kind, c in row["collectives"].items():
            if kind in collectives and "bytes_per_exec" in c \
                    and c["bytes_per_exec"] is not None:
                collectives[kind]["bytes"] = (
                    collectives[kind].get("bytes", 0)
                    + c["bytes_per_exec"] * max(execs, 1))
    for c in collectives.values():
        c["ms"] = round(c["ms"], 4)

    wall_s = wall_ms / 1e3 if wall_ms > 0 else None
    ledger = {
        "peak_flops": peak_flops,
        "peak_flops_source": peak_src,
        "model_flops_total": model_flops_total if flops_known else None,
        "model_flops_per_s": round(model_flops_total / wall_s, 3)
        if flops_known and wall_s else None,
        "mfu": round(model_flops_total / wall_s / peak_flops, 6)
        if flops_known and wall_s and peak_flops else None,
        "goodput_busy_frac": round(busy_frac, 6),
        "steps": steps,
        "wall_ms_per_step": round(wall_ms / steps, 4)
        if steps else None,
        "device_busy_ms_per_step": round(device_busy_ms / steps, 4)
        if steps else None,
        "host_gap_ms_per_step": round(host_gap_ms / steps, 4)
        if steps else None,
    }

    host: Dict[str, dict] = {}
    for name, _ts, dur in tl.host_spans:
        h = host.setdefault(name, {"count": 0, "ms": 0.0})
        h["count"] += 1
        h["ms"] += dur / 1e3
    for h in host.values():
        h["ms"] = round(h["ms"], 4)

    return {
        "kind": "device_trace_summary",
        "label": label,
        "platform": platform,
        "unix_time": round(time.time(), 3),
        "steps": steps,
        "events_total": tl.events_total,
        "device_ops": len(tl.device_ops),
        "empty": not tl.device_ops,
        "wall_ms": round(wall_ms, 4),
        "device_busy_ms": round(device_busy_ms, 4),
        "host_gap_ms": round(host_gap_ms, 4),
        "busy_frac": round(busy_frac, 6),
        "categories": {c: {"count": v["count"], "ms": round(v["ms"], 4)}
                       for c, v in categories.items()},
        "collectives": collectives,
        "comm_ms": round(comm_ms, 4),
        "comm_overlap_frac": round(comm_overlap, 6),
        "comm_traced_ms_per_step": round(comm_ms / steps, 4)
        if steps else None,
        "sites": sites,
        "unattributed_modules": unattributed,
        "ledger": ledger,
        "host_annotations": host,
    }


# ---------------------------------------------------------------------------
# summary recording: gauges + sink artifact + last-summary slot
# ---------------------------------------------------------------------------
_last_lock = threading.Lock()
_last: Optional[dict] = None


def last_summary() -> Optional[dict]:
    """The most recent recorded trace summary (what the flight
    recorder attaches to watchdog/rollback dumps); None before any
    capture completed."""
    with _last_lock:
        return _last


def reset() -> None:
    global _last
    with _last_lock:
        _last = None


def record_summary(summary: dict) -> dict:
    """Fold a summary into the registry gauges, persist it through an
    active sink as ``trace_summary.json`` (atomic rewrite, prom-file
    latest-wins contract), and remember it for flight dumps. Never
    raises — capture teardown must not take the hot loop down.

    Degraded summaries (a skipped capture, a parse error — no
    ``wall_ms``) are NOT recorded: they stay visible on the capture
    object, but must not clobber the last good summary, feed the
    gauges, or overwrite the sink artifact with a document that
    violates its own schema. They are counted instead
    (``trace/windows_degraded``)."""
    global _last
    if "wall_ms" not in summary:
        try:
            registry().counter("trace/windows_degraded").add(1)
        except Exception:
            pass
        return summary
    try:
        reg = registry()
        reg.gauge("trace/device_busy_ms").set(summary["device_busy_ms"])
        reg.gauge("trace/host_gap_ms").set(summary["host_gap_ms"])
        reg.gauge("trace/goodput_busy_frac").set(summary["busy_frac"])
        reg.gauge("trace/device_ops").set(float(summary["device_ops"]))
        # measured comm: coexists with the apportioned
        # phase/comm_measured_ms and the modeled phase/comm_ms
        per_step = summary.get("comm_traced_ms_per_step")
        reg.gauge("phase/comm_traced_ms").set(
            per_step if per_step is not None else summary["comm_ms"])
        reg.gauge("phase/comm_overlap_frac").set(
            summary["comm_overlap_frac"])
        for kind, c in summary.get("collectives", {}).items():
            reg.gauge(f"trace/comm/{kind}_ms").set(c["ms"])
        led = summary.get("ledger") or {}
        if led.get("mfu") is not None:
            reg.gauge("trace/mfu").set(led["mfu"])
        if led.get("model_flops_per_s") is not None:
            reg.gauge("trace/model_flops_per_s").set(
                led["model_flops_per_s"])
        reg.counter("trace/windows_recorded").add(1)
    except Exception:
        pass
    with _last_lock:
        _last = summary
    try:
        from . import sink as _sink

        s = _sink.active_sink()
        if s is not None:
            path = os.path.join(s.directory, "trace_summary.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(summary, f)
            os.replace(tmp, path)
    except Exception:
        pass
    return summary


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------
class capture:  # noqa: N801 - context manager, lowercase like scope
    """Wrap a window of hot-loop iterations in a jax profiler trace and
    parse it on exit::

        with device_trace.capture(steps=4, label="hybrid.step") as cap:
            for _ in range(4):
                step()           # materialize each step's output!
        cap.summary              # dict (see summarize)

    The caller must SYNC the wrapped work (fetch a result leaf) before
    the block ends — device work still in flight when the trace stops
    is cut off, exactly like any profiler window.

    ``log_dir=None`` captures into a temp dir deleted after parsing
    (``keep_files=True`` keeps it; ``cap.trace_file`` points at the
    parsed artifact). ``steps`` may be (re)assigned inside the block —
    engine wrappers set it to the measured tick count before exit.
    Only one jax trace can run per process: if another is active, the
    capture degrades to a no-op with ``summary = {"skipped": ...}``
    rather than raising into the hot loop. Parse failures land in
    ``summary["error"]``; degraded summaries stay on the capture
    object but are NOT folded into gauges / the sink artifact / the
    flight slot (:func:`record_summary` counts them as
    ``trace/windows_degraded`` instead).
    """

    def __init__(self, log_dir: Optional[str] = None,
                 steps: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 label: str = "trace", keep_files: bool = False):
        self.log_dir = log_dir
        self.steps = steps
        self.peak_flops = peak_flops
        self.label = label
        self.keep_files = keep_files or log_dir is not None
        self.summary: Optional[dict] = None
        self.trace_file: Optional[str] = None
        self._dir: Optional[str] = None
        self._tmp = False
        self._started = False

    def __enter__(self) -> "capture":
        import jax

        if self.log_dir is None:
            self._dir = tempfile.mkdtemp(prefix="ptpu-trace-")
            self._tmp = True
        else:
            os.makedirs(self.log_dir, exist_ok=True)
            self._dir = self.log_dir
        try:
            # the .trace.json.gz is part of the standard export — no
            # create_perfetto_trace re-encode needed (find_trace_file
            # reads either spelling)
            jax.profiler.start_trace(self._dir)
            self._started = True
        except Exception as e:
            # another trace active (profiler.enable(trace_dir=...) or a
            # nested window): degrade, don't break the hot loop
            self.summary = {"kind": "device_trace_summary",
                            "label": self.label, "skipped": str(e),
                            "empty": True}
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._started:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as e:
                self.summary = {"kind": "device_trace_summary",
                                "label": self.label,
                                "error": f"stop_trace: {e}",
                                "empty": True}
            else:
                if exc_type is None:
                    self._parse()
        if self.summary is not None and exc_type is None:
            record_summary(self.summary)
        if self._tmp and not self.keep_files and self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            self.trace_file = None
        return False

    def _parse(self) -> None:
        path = find_trace_file(self._dir)
        if path is None:
            self.summary = {"kind": "device_trace_summary",
                            "label": self.label,
                            "error": "no trace file exported",
                            "empty": True}
            return
        self.trace_file = path
        try:
            doc = load_trace_events(path)
            self.summary = summarize(doc, steps=self.steps,
                                     peak_flops=self.peak_flops,
                                     label=self.label)
        except TraceParseError as e:
            self.summary = {"kind": "device_trace_summary",
                            "label": self.label, "error": str(e),
                            "empty": True}


#: package-level spellings (``profiler.trace_capture`` /
#: ``profiler.last_trace_summary``) — module-local names stay short
trace_capture = capture
last_trace_summary = last_summary


class TraceWindow:
    """Windowed capture scheduler: trace iterations N..N+length-1,
    every ``every`` iterations (``every=0``: one window only)::

        win = TraceWindow(length=2, every=100, start=10)
        for i in range(steps):
            with win.step():
                trainer.step(batch)
        win.last            # newest summary; win.summaries holds all

    ``max_windows`` bounds how many windows fire (0 = unbounded); each
    window is one :class:`capture` (steps=length), so summaries carry
    the per-step normalizations. Window starts that collide with an
    already-running jax trace are skipped and counted
    (``win.skipped``)."""

    def __init__(self, length: int = 2, every: int = 0, start: int = 0,
                 log_dir: Optional[str] = None,
                 peak_flops: Optional[float] = None,
                 label: str = "window", max_windows: int = 0,
                 keep_files: bool = False):
        if length < 1:
            raise ValueError("length must be >= 1")
        if every and every < length:
            raise ValueError("every must be 0 or >= length "
                             "(windows must not overlap)")
        self.length = int(length)
        self.every = int(every)
        self.start = int(start)
        self.log_dir = log_dir
        self.peak_flops = peak_flops
        self.label = label
        self.max_windows = int(max_windows)
        self.keep_files = keep_files
        self.summaries: List[dict] = []
        self.skipped = 0
        self._i = 0
        self._cap: Optional[capture] = None
        self._end = -1

    @property
    def last(self) -> Optional[dict]:
        return self.summaries[-1] if self.summaries else None

    def _should_start(self, i: int) -> bool:
        if self.max_windows and len(self.summaries) >= self.max_windows:
            return False
        if i < self.start:
            return False
        if self.every:
            return (i - self.start) % self.every == 0
        return i == self.start

    def step(self) -> "_WindowStep":
        """Context manager wrapping ONE hot-loop iteration."""
        return _WindowStep(self)


class _WindowStep:
    __slots__ = ("_w",)

    def __init__(self, window: TraceWindow):
        self._w = window

    def __enter__(self):
        w = self._w
        if w._cap is None and w._should_start(w._i):
            n = len(w.summaries)
            sub = os.path.join(w.log_dir, f"window-{n}") \
                if w.log_dir else None
            cap = capture(log_dir=sub, steps=w.length,
                          peak_flops=w.peak_flops,
                          label=f"{w.label}#{n}",
                          keep_files=w.keep_files)
            cap.__enter__()
            if cap.summary is not None and "skipped" in cap.summary:
                # another jax trace is live — don't fight it; release
                # the temp dir __enter__ already made (nothing was
                # captured into it, and __exit__ will never run)
                cap._started = False
                if cap._tmp and cap._dir:
                    shutil.rmtree(cap._dir, ignore_errors=True)
                w.skipped += 1
            else:
                w._cap = cap
                w._end = w._i + w.length - 1
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        w = self._w
        try:
            if w._cap is not None and (w._i >= w._end
                                       or exc_type is not None):
                cap = w._cap
                w._cap = None
                cap.__exit__(exc_type, exc, tb)
                if exc_type is None and cap.summary is not None:
                    w.summaries.append(cap.summary)
        finally:
            w._i += 1
        return False
