"""Inference engine: load a saved program + params and run WITHOUT the
Python model class.

TPU-native analogue of the reference inference stack (reference:
paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor —
loads a ProgramDesc + persistables, runs analysis/fusion passes, executes
with NaiveExecutor; CreatePaddlePredictor factory, api/paddle_api.h).
Translation per SURVEY §7: the serialized "program" is a jax.export
StableHLO portable artifact (versioned, runnable across processes and
jax versions), the optimization passes are XLA's (run at load-time
compile), and the executor is the XLA runtime — there is no separate
NaiveExecutor to maintain.

    config = Config(model_dir)          # wrote by paddle_tpu.jit.save
    predictor = create_predictor(config)
    out, = predictor.run([np_input])
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """reference: AnalysisConfig (paddle_analysis_config.h). GPU/TRT/IR
    toggles have no TPU meaning: XLA always optimizes; methods are kept as
    accepted no-ops for API compatibility."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file
        self._device = None

    # --- accepted-for-compat toggles (XLA owns optimization on TPU) ------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = ("gpu", device_id)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def switch_ir_optim(self, x=True):
        pass

    def enable_memory_optim(self):
        pass

    def enable_tensorrt_engine(self, **kw):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class Predictor:
    """Runs a ``paddle_tpu.jit.save``-d model from its on-disk artifact.

    The forward is the deserialized jax.export call — the Python class
    that built the model is NOT needed (the reference's key property:
    AnalysisPredictor runs from ProgramDesc alone)."""

    def __init__(self, path: str):
        import jax.export

        self.path = path
        with open(path + ".pdmodel.bin", "rb") as f:
            self._exported = jax.export.deserialize(bytearray(f.read()))
        with open(path + ".pdparams", "rb") as f:
            state = pickle.load(f)
        with open(path + ".pdmeta", "rb") as f:
            self._meta = pickle.load(f)
        pnames = self._meta["param_names"]
        bnames = self._meta.get("buffer_names", [])
        self._params = [np.asarray(state[n]) for n in pnames]
        self._buffers = [np.asarray(state[n]) for n in bnames]
        self._input_names = self._meta.get("input_names") or [
            f"x{i}" for i in range(len(self._meta.get("input_specs", [])))]

    # --- paddle inference API surface ------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def run(self, inputs: Sequence[np.ndarray]):
        """Feed host arrays, return host arrays (fetch)."""
        outs = self._exported.call(self._params, self._buffers,
                                   *[np.asarray(x) for x in inputs])
        import jax

        flat = jax.tree_util.tree_leaves(outs)
        return [np.asarray(o) for o in flat]

    __call__ = run


def create_predictor(config: Config) -> Predictor:
    """reference: CreatePaddlePredictor (analysis_predictor.cc)."""
    if not config.model_path:
        raise ValueError("Config needs the saved model path")
    if not os.path.exists(config.model_path + ".pdmodel.bin"):
        raise FileNotFoundError(
            f"{config.model_path}.pdmodel.bin not found — save with "
            "paddle_tpu.jit.save(layer, path, input_spec=[...])")
    return Predictor(config.model_path)
