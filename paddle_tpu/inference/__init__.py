"""Inference engine: load a saved program + params and run WITHOUT the
Python model class.

TPU-native analogue of the reference inference stack (reference:
paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor —
loads a ProgramDesc + persistables, runs analysis/fusion passes, executes
with NaiveExecutor; CreatePaddlePredictor factory, api/paddle_api.h).
Translation per SURVEY §7: the serialized "program" is a jax.export
StableHLO portable artifact (versioned, runnable across processes and
jax versions), the optimization passes are XLA's (run at load-time
compile), and the executor is the XLA runtime — there is no separate
NaiveExecutor to maintain.

    config = Config(model_dir)          # wrote by paddle_tpu.jit.save
    predictor = create_predictor(config)
    out, = predictor.run([np_input])
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "ServingPredictor"]


def __getattr__(name):   # PEP 562: ServingPredictor pulls in the serving
    if name == "ServingPredictor":      # stack (jax) only when asked for
        from .serving import ServingPredictor

        return ServingPredictor
    raise AttributeError(name)


class Config:
    """reference: AnalysisConfig (paddle_analysis_config.h). GPU/TRT/IR
    toggles have no TPU meaning: XLA always optimizes; methods are kept as
    accepted no-ops for API compatibility."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file
        self._device = None

    # --- accepted-for-compat toggles (XLA owns optimization on TPU) ------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = ("gpu", device_id)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def switch_ir_optim(self, x=True):
        pass

    def enable_memory_optim(self):
        pass

    def enable_tensorrt_engine(self, **kw):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class Predictor:
    """Runs a ``paddle_tpu.jit.save``-d model from its on-disk artifact.

    The forward is the deserialized jax.export call — the Python class
    that built the model is NOT needed (the reference's key property:
    AnalysisPredictor runs from ProgramDesc alone)."""

    def __init__(self, path: str):
        import jax
        import jax.export

        self.path = path
        with open(path + ".pdmodel.bin", "rb") as f:
            self._exported = jax.export.deserialize(bytearray(f.read()))
        with open(path + ".pdparams", "rb") as f:
            state = pickle.load(f)
        with open(path + ".pdmeta", "rb") as f:
            self._meta = pickle.load(f)
        pnames = self._meta["param_names"]
        bnames = self._meta.get("buffer_names", [])
        params = [np.asarray(state[n]) for n in pnames]
        # int8: current artifacts (meta['int8_compute']) embed the int8
        # dot_generals in the exported program — weights are int8 state
        # entries, nothing to do here. LEGACY artifacts shipped a
        # .pdint8 sidecar instead; dequantize those into the param slots
        # (the old slim→AnalysisPredictor handoff shape).
        int8_compute = bool(self._meta.get("int8_compute"))
        legacy_sidecar = not int8_compute and \
            os.path.exists(path + ".pdint8")
        self.quantized = int8_compute or legacy_sidecar
        if legacy_sidecar:
            with open(path + ".pdint8", "rb") as f:
                int8 = pickle.load(f)
            by_name = dict(zip(pnames, range(len(pnames))))
            for lname, ent in int8.items():
                pidx = by_name.get(lname + ".inner.weight")
                if pidx is None:
                    # the fp32 copy was ZEROED at save time — serving
                    # without the sidecar weight would be silently wrong
                    raise ValueError(
                        f"int8 sidecar layer {lname!r} has no matching "
                        f"param {lname + '.inner.weight'!r} in the saved "
                        "artifact; the artifact is inconsistent")
                q = ent["int8_weight"].astype(np.float32)
                scales = ent["scales"]
                if scales.size > 1:        # channel-wise
                    shape = [1] * q.ndim
                    shape[ent["channel_axis"]] = -1
                    scale = scales.reshape(shape)
                else:
                    scale = scales[0]
                params[pidx] = (q * scale / 127.0).astype(
                    params[pidx].dtype)
        # weights live ON DEVICE across run() calls (serving: no
        # host→device re-upload per request)
        self._params = jax.device_put(params)
        self._buffers = jax.device_put(
            [np.asarray(state[n]) for n in bnames])
        self._input_names = self._meta.get("input_names") or [
            f"x{i}" for i in range(len(self._meta.get("input_specs", [])))]
        # the deserialized artifact's .call re-enters program dispatch on
        # every invocation; a jit wrapper caches the executable lookup —
        # serving-path dispatch cost drops to a dict hit
        self._jit_calls = {}
        # batch-size buckets: per-bucket artifacts, loaded lazily.
        # LRU-capped: a serving front-end can legitimately save dozens of
        # buckets, and each deserialized executable pins compiled code +
        # a jit wrapper — evict cold buckets (reloadable from disk) and
        # count it (cache_evict/predictor_exec in the profiler registry).
        from ..utils.lru import LRUCache

        self._buckets = sorted(self._meta.get("batch_buckets", []))
        self._bucket_exec = LRUCache(
            Predictor.BUCKET_EXEC_CACHE_SIZE, "predictor_exec",
            on_evict=lambda _b, exe: self._jit_calls.pop(id(exe), None))
        self._base_batch = None
        specs = self._meta.get("input_specs")
        if specs and len(specs[0][0]) > 0:
            self._base_batch = int(specs[0][0][0])

    #: LRU capacity for lazily-deserialized per-bucket executables
    BUCKET_EXEC_CACHE_SIZE = 8

    def _executable_for(self, n: int):
        """Smallest bucket >= n (or the base artifact when it fits)."""
        import jax.export

        if self._base_batch is not None and n == self._base_batch:
            return self._exported, n
        for b in self._buckets:
            if b >= n:
                if b not in self._bucket_exec:
                    with open(f"{self.path}.pdmodel.b{b}.bin", "rb") as f:
                        self._bucket_exec[b] = jax.export.deserialize(
                            bytearray(f.read()))
                return self._bucket_exec[b], b
        if self._base_batch is not None and n < self._base_batch:
            return self._exported, self._base_batch
        raise ValueError(
            f"batch {n} exceeds every saved bucket "
            f"{self._buckets or [self._base_batch]}; re-save with a "
            "larger batch_buckets entry")

    # --- paddle inference API surface ------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def run(self, inputs: Sequence[np.ndarray]):
        """Feed host arrays, return host arrays (fetch). Requests whose
        batch is not a saved size are padded up to the nearest bucket and
        the outputs sliced back."""
        import jax

        # device-resident inputs pass through (serving hot path: no
        # host round-trip when the request is already on device)
        arrs = [x if isinstance(x, jax.Array) else np.asarray(x)
                for x in inputs]
        # batched-input indices come from save-time meta (exact — the
        # same rule jit.save bucketed with); heuristic only for legacy
        # artifacts predating the field
        bin_idx = self._meta.get("batched_inputs")
        first_b = bin_idx[0] if bin_idx else 0
        n = int(arrs[first_b].shape[0]) \
            if len(arrs) > first_b and arrs[first_b].ndim else None
        if n == 0:
            raise ValueError("empty batch: no saved executable can run "
                             "batch 0")
        exe, bucket = (self._exported, None) if n is None else \
            self._executable_for(n)
        if bucket is not None and bucket != n:
            def is_batched(i, a):
                if bin_idx is not None:
                    return i in bin_idx
                return bool(a.ndim) and a.shape[0] == n
            arrs = [np.concatenate(
                [a, np.repeat(a[-1:], bucket - n, axis=0)], axis=0)
                if is_batched(i, a) else a for i, a in enumerate(arrs)]
        outs = self._cached_call(exe)(self._params, self._buffers, *arrs)
        flat = jax.tree_util.tree_leaves(outs)
        res = [np.asarray(o) for o in flat]
        if bucket is not None and bucket != n:
            batched = self._meta.get("batched_outputs") \
                or self._batched_outputs(exe, bucket)
            res = [r[:n] if (batched[i] if batched and i < len(batched)
                             else r.ndim and r.shape[0] == bucket) else r
                   for i, r in enumerate(res)]
        return res

    def _cached_call(self, exe):
        import jax

        fn = self._jit_calls.get(id(exe))
        if fn is None:
            fn = self._jit_calls[id(exe)] = jax.jit(exe.call)
        return fn

    def _batched_outputs(self, exe, bucket):
        """Legacy fallback (artifacts without meta['batched_outputs']):
        compare this executable's output avals against the base
        artifact's — dims that track the bucket size are batched. None
        when the base batch equals the bucket (no signal; the caller
        falls back to the shape-match heuristic)."""
        if self._base_batch is None or self._base_batch == bucket or \
                not hasattr(exe, "out_avals") or \
                not hasattr(self._exported, "out_avals"):
            return None
        out = []
        for a, b in zip(exe.out_avals, self._exported.out_avals):
            out.append(len(a.shape) > 0 and a.shape[0] == bucket
                       and b.shape[0] == self._base_batch
                       and a.shape[1:] == b.shape[1:])
        return out

    __call__ = run


def create_predictor(config: Config) -> Predictor:
    """reference: CreatePaddlePredictor (analysis_predictor.cc)."""
    if not config.model_path:
        raise ValueError("Config needs the saved model path")
    if not os.path.exists(config.model_path + ".pdmodel.bin"):
        raise FileNotFoundError(
            f"{config.model_path}.pdmodel.bin not found — save with "
            "paddle_tpu.jit.save(layer, path, input_spec=[...])")
    return Predictor(config.model_path)
