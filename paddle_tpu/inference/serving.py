"""ServingPredictor: host the continuous-batching engine behind the
Predictor surface.

``inference.Predictor`` runs a saved artifact with a fixed program per
batch bucket — right for classification-style traffic, wrong for
autoregressive decode where requests have ragged lengths and finish at
different times. ``ServingPredictor`` keeps the same calling shape
(``run([inputs]) -> [outputs]``, ``get_input_names``) but is backed by
``paddle_tpu.serving.ServingEngine``, so a deployment written against
the Predictor API can switch to continuous batching by swapping the
constructor.

The engine needs live model weights (the paged tick re-stages KV pages
every step — a frozen jax.export artifact can't host that), so this
predictor is built FROM a ``GPT`` model, optionally restoring state
saved by ``paddle_tpu.save``::

    pred = ServingPredictor(model, max_new_tokens=64,
                            num_slots=8, page_size=16)
    out_ids, out_lens = pred.run([token_batch, lengths])

Streaming submission is available on the underlying engine
(``pred.engine.submit`` / ``pred.engine.run``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["ServingPredictor"]


class ServingPredictor:
    """Predictor-shaped front end over ``serving.ServingEngine``."""

    def __init__(self, model, max_new_tokens: int = 32,
                 state_path: Optional[str] = None, **engine_knobs):
        from ..serving import ServingConfig, ServingEngine

        if state_path is not None:
            import paddle_tpu as _paddle

            model.set_state_dict(_paddle.load(state_path))
        self.max_new_tokens = int(max_new_tokens)
        self.engine = ServingEngine(model, ServingConfig(**engine_knobs))

    def get_input_names(self) -> List[str]:
        return ["tokens", "lengths"]

    def run(self, inputs: Sequence[np.ndarray]):
        """inputs: ``[tokens [N, T] int, lengths [N] int (optional)]``.
        Rows are submitted as independent requests (``lengths`` strips
        right padding; omitted means every row is full length) and
        served concurrently by the engine. Returns
        ``[ids [N, max_new_tokens], lengths [N]]`` — rows shorter than
        ``max_new_tokens`` (EOS) are right-padded with the EOS id."""
        toks = np.asarray(inputs[0], np.int32)
        if toks.ndim != 2:
            raise ValueError("tokens must be [N, T]")
        n, t = toks.shape
        lens = (np.asarray(inputs[1], np.int64).reshape(-1)
                if len(inputs) > 1 else np.full(n, t, np.int64))
        rids = [self.engine.submit(toks[i, :int(lens[i])],
                                   self.max_new_tokens)
                for i in range(n)]
        results = self.engine.run()
        eos = self.engine.config.eos_token_id
        out = np.full((n, self.max_new_tokens),
                      eos if eos is not None else 0, np.int32)
        out_lens = np.zeros(n, np.int64)
        for i, rid in enumerate(rids):
            row = results[rid][:self.max_new_tokens]
            out[i, :row.shape[0]] = row
            out_lens[i] = row.shape[0]
        self.engine.reset_results()
        return [out, out_lens]

    __call__ = run
