"""Standalone gradient accumulation (reference: fleet
meta_optimizers/gradient_merge_optimizer.py + the
GradMergeAllReduceOpHandle, details/grad_merge_all_reduce_op_handle.cc —
accumulate k micro-steps, then apply one update).

Round 1 only offered accumulation inside the pipeline's microbatch loop;
this is the eager-API form: wrap any optimizer, call step() every
micro-step, the wrapped update fires every ``k_steps``-th call. Inside a
compiled trainer the same thing is a lax.scan over microbatches
(strategy.gradient_merge handles that path).
"""
from __future__ import annotations

__all__ = ["GradientMerge"]


class GradientMerge:
    def __init__(self, optimizer, k_steps: int = 1, avg: bool = True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.inner_opt = optimizer
        self.k_steps = k_steps
        self.avg = avg
        self._count = 0

    def __getattr__(self, name):
        return getattr(self.inner_opt, name)

    @property
    def merged_step(self) -> int:
        """Number of APPLIED (merged) updates so far."""
        return self._count // self.k_steps

    def step(self):
        """Accumulate this micro-step's grads; apply on every k-th call.

        Grads keep summing into ``param.grad`` between applies (the tape
        accumulates); ``clear_grad`` only runs after an apply."""
        self._count += 1
        if self._count % self.k_steps:
            return False
        if self.avg and self.k_steps > 1:
            for p in self.inner_opt._parameter_list or []:
                if p.grad is not None:
                    p.grad._value = p.grad._value / self.k_steps
        self.inner_opt.step()
        return True

    def clear_grad(self):
        """No-op mid-accumulation; clears after an applied step."""
        if self._count % self.k_steps == 0:
            self.inner_opt.clear_grad()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        applied = self.step()
        self.clear_grad()
        return ([], []) if applied else ([], [])
