"""paddle.optimizer equivalent."""
from . import lr  # noqa: F401
from .grad_merge import GradientMerge  # noqa: F401
from .optimizer import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,  # noqa: F401
                        Lamb, Lars, Momentum, Optimizer, RMSProp)


class L2Decay:
    """reference: fluid/regularizer.py L2DecayRegularizer."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    coeff = property(lambda self: self._coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    coeff = property(lambda self: self._coeff)
