"""Optimizers.

TPU-native analogue of the reference optimizer ops
(reference: paddle/fluid/operators/optimizers/ — sgd_op, momentum_op,
adam_op.cu, lamb_op.cu…; python API python/paddle/optimizer/).

Design: each optimizer is a *functional* update rule
``_update(p, g, state, lr) -> (new_p, new_state)`` lifted over the whole
parameter list in ONE jit-compiled XLA computation per step, so the eager
``opt.step()`` costs a single device dispatch (the reference launches one
CUDA kernel per parameter — SURVEY.md §3.1 flags that as a hot loop; this
is the TPU fix). The same rule object plugs into the distributed strategy
compiler for the pjit path.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..autograd import no_grad
from ..framework.tensor import Parameter, Tensor
from .clip import apply_grad_clip
from .lr import LRScheduler


class Optimizer:
    _rule_name = "base"

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._weight_decay = 0.0
            self._decay_mode = "none"
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
            self._decay_mode = "l2"          # L2 regularizer → grad += wd * p
        else:  # L1Decay / L2Decay object (regularizer.py)
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay,
                                                       "coeff", 0.0)))
            self._decay_mode = "l1" if "L1" in \
                type(weight_decay).__name__ else "l2"
        self._accumulators: Dict[int, dict] = {}
        self._global_step = 0
        self._jitted = None

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "optimizer's learning rate can't be LRScheduler when invoke "
                "this API, because this will lead to conflict.")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate,
                                                 LRScheduler) else None

    # -- state -------------------------------------------------------------
    def _init_state(self, p: Parameter) -> dict:
        return {}

    def _state_for(self, p: Parameter) -> dict:
        s = self._accumulators.get(id(p))
        if s is None:
            s = self._init_state(p)
            self._accumulators[id(p)] = s
        return s

    # -- the update rule (override) ---------------------------------------
    def _update(self, p, g, state: dict, lr, step, wd=0.0):
        raise NotImplementedError

    def _decoupled_wd(self, p: Parameter) -> float:
        """Per-parameter decoupled weight-decay coefficient (AdamW/Lamb/Lars
        override; 0 disables)."""
        return 0.0

    # -- step --------------------------------------------------------------
    @no_grad()
    def step(self):
        params = [p for p in self._parameter_list
                  if p.trainable and p.grad is not None]
        from ..core import flags as _flags

        if _flags.get_flags("enable_unused_var_check") and params:
            # reference FLAGS_enable_unused_var_check
            # (framework/unused_var_check.cc) flags declared-but-unused
            # op inputs; the tape analogue is a trainable parameter that
            # backward never reached — it will silently not train.
            # Gated on `params`: a step with NO grads anywhere is an
            # empty/skipped step, not disconnection.
            import warnings

            for p in self._parameter_list:
                if p.trainable and p.grad is None:
                    warnings.warn(
                        f"Parameter {getattr(p, 'name', '?')} is "
                        "trainable but received no gradient this step — "
                        "it is disconnected from the loss",
                        RuntimeWarning, stacklevel=2)
        if not params:
            return
        if self._grad_clip is not None:
            apply_grad_clip(self._grad_clip, params)
        self._global_step += 1
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._global_step, jnp.int32)
        states = [self._state_for(p) for p in params]
        p_vals = [p._value for p in params]
        g_vals = [p.grad._value for p in params]
        lrs = tuple(p.optimize_attr.get("learning_rate", 1.0) for p in params)
        def _reg_sig(p):
            if p.regularizer is None:
                return -1.0, "l2"
            coeff = float(getattr(p.regularizer, "_coeff",
                                  getattr(p.regularizer, "coeff", 0.0)))
            kind = "l1" if "L1" in type(p.regularizer).__name__ else "l2"
            return coeff, kind

        regs = tuple(_reg_sig(p) for p in params)
        wds = tuple(self._decoupled_wd(p) for p in params)

        sig = (lrs, regs, wds, tuple(id(p) for p in params))
        if self._jitted is not None and self._jit_sig != sig:
            self._jitted = None
        if self._jitted is None:
            decay_mode = self._decay_mode
            wd = self._weight_decay
            update = self._update

            def fused(p_vals, g_vals, states, lr, step_no):
                new_ps, new_ss = [], []
                for p, g, s, plr, reg, pwd in zip(p_vals, g_vals, states,
                                                  fused._lrs, fused._regs,
                                                  fused._wds):
                    g = g.astype(jnp.float32) if g.dtype == jnp.bfloat16 else g
                    rcoeff, rkind = reg
                    if rcoeff >= 0.0:
                        # per-param regularizer (regularizer.py L1/L2Decay)
                        g = g + (rcoeff * jnp.sign(p) if rkind == "l1"
                                 else rcoeff * p)
                    elif decay_mode == "l2" and wd:
                        g = g + wd * p
                    elif decay_mode == "l1" and wd:
                        g = g + wd * jnp.sign(p)
                    np_, ns = update(p, g, s, lr * plr, step_no, wd=pwd)
                    new_ps.append(np_)
                    new_ss.append(ns)
                return new_ps, new_ss

            fused._lrs = lrs
            fused._regs = regs
            fused._wds = wds
            self._jitted = jax.jit(fused)
            self._jit_sig = sig

        new_p, new_s = self._jitted(p_vals, g_vals, states, lr, step_no)
        for p, v, s in zip(params, new_p, new_s):
            p._value = v
            self._accumulators[id(p)] = s

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Dygraph minimize: backward + step
        (reference: python/paddle/optimizer/optimizer.py minimize)."""
        loss.backward()
        self.step()
        return [], []

    @no_grad()
    def clear_grad(self):
        for p in self._parameter_list or []:
            p.grad = None

    clear_gradients = clear_grad

    # -- checkpoint --------------------------------------------------------
    def state_dict(self) -> dict:
        sd = {}
        for i, p in enumerate(self._parameter_list or []):
            s = self._accumulators.get(id(p))
            if s:
                for k, v in s.items():
                    sd[f"{p.name or i}_{k}"] = Tensor(v) \
                        if not isinstance(v, Tensor) else v
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        sd["global_step"] = self._global_step
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list or []):
            s = self._init_state(p)
            found = False
            for k in list(s.keys()):
                key = f"{p.name or i}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    s[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    found = True
            if found:
                self._accumulators[id(p)] = s
        self._jitted = None

    set_dict = set_state_dict


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc"""

    _rule_name = "sgd"

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _update(self, p, g, state, lr, step, wd=0.0):
        return (p - (lr * g).astype(p.dtype)), state


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.h"""

    _rule_name = "momentum"

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, p, g, state, lr, step, wd=0.0):
        v = self._momentum * state["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v).astype(p.dtype)
        else:
            new_p = p - (lr * v).astype(p.dtype)
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.cu — the reference launches
    one kernel per param (SURVEY §3.1 hot loop); here all params update in
    one fused XLA computation."""

    _rule_name = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p._value.shape, jnp.float32),
                "moment2": jnp.zeros(p._value.shape, jnp.float32)}

    def _decayed_update(self, p, g, state, lr, step, decoupled_wd=0.0):
        g = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        pf = p.astype(jnp.float32)
        upd = lr * (mhat / (jnp.sqrt(vhat) + self._epsilon)
                    + decoupled_wd * pf)
        return (pf - upd).astype(p.dtype), {"moment1": m, "moment2": v}

    def _update(self, p, g, state, lr, step, wd=0.0):
        return self._decayed_update(p, g, state, lr, step)


class AdamW(Adam):
    """reference: python/paddle/optimizer/adamw.py (decoupled decay)."""

    _rule_name = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if isinstance(
            weight_decay, (int, float)) else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decay_mode = "decoupled"

    def _decoupled_wd(self, p):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return self._coeff

    def _update(self, p, g, state, lr, step, wd=0.0):
        return self._decayed_update(p, g, state, lr, step, decoupled_wd=wd)


class Adamax(Optimizer):
    """reference: operators/optimizers/adamax_op.h"""

    _rule_name = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros(p._value.shape, jnp.float32),
                "inf_norm": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        t = step.astype(jnp.float32)
        new_p = p - (lr / (1 - self._beta1 ** t) * m /
                     (u + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    """reference: operators/optimizers/adagrad_op.h"""

    _rule_name = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full(p._value.shape, self._init_val,
                                   jnp.float32)}

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        m = state["moment"] + g * g
        new_p = p - (lr * g / (jnp.sqrt(m) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    """reference: operators/optimizers/adadelta_op.h"""

    _rule_name = "adadelta"

    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros(p._value.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * state["avg_squared_update"] + \
            (1 - self._rho) * upd * upd
        return (p - (lr * upd).astype(p.dtype)), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    """reference: operators/optimizers/rmsprop_op.h"""

    _rule_name = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        s = {"mean_square": jnp.zeros(p._value.shape, jnp.float32),
             "momentum": jnp.zeros(p._value.shape, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new_s = {"mean_square": ms, "momentum": mom}
        if self._centered:
            new_s["mean_grad"] = mg
        return (p - mom.astype(p.dtype)), new_s


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.h (large-batch LAMB)."""

    _rule_name = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lamb_wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p._value.shape, jnp.float32),
                "moment2": jnp.zeros(p._value.shape, jnp.float32)}

    def _decoupled_wd(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._lamb_wd

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        pf = p.astype(jnp.float32)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * pf
        p_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), \
            {"moment1": m, "moment2": v}


class Lars(Momentum):
    """LARS momentum (reference: operators/optimizers/lars_momentum_op.cu)."""

    _rule_name = "lars"

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, name=None, exclude_from_weight_decay=None,
                 epsilon=0.0):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude_names = list(exclude_from_weight_decay or [])

    def _decoupled_wd(self, p):
        if any(frag in (p.name or "") for frag in self._exclude_names):
            return 0.0
        return self._lars_wd

    def _update(self, p, g, state, lr, step, wd=0.0):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        p_norm = jnp.linalg.norm(pf)
        g_norm = jnp.linalg.norm(g)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm /
            (g_norm + wd * p_norm + self._eps), 1.0)
        v = self._momentum * state["velocity"] + lr * local_lr * (
            g + wd * pf)
        return (pf - v).astype(p.dtype), {"velocity": v}
