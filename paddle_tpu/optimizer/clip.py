"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue/ByNorm/ByGlobalNorm)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


@jax.jit
def _global_norm(grads):
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in grads))


def apply_grad_clip(clip, params):
    """Mutates p.grad in place according to the clip object."""
    from ..nn import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

    clipped = [p for p in params if p.grad is not None and
               getattr(p, "need_clip", True)]
    if not clipped:
        return
    if isinstance(clip, ClipGradByValue):
        for p in clipped:
            p.grad._value = jnp.clip(p.grad._value, clip.min, clip.max)
    elif isinstance(clip, ClipGradByNorm):
        for p in clipped:
            g = p.grad._value
            n = jnp.linalg.norm(g.astype(jnp.float32))
            scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
            p.grad._value = (g * scale).astype(g.dtype)
    elif isinstance(clip, ClipGradByGlobalNorm):
        grads = [p.grad._value for p in clipped]
        gn = _global_norm(grads)
        scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(gn, 1e-12))
        for p in clipped:
            p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    else:
        raise TypeError(f"Unknown grad clip type: {type(clip)}")


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros([]))
    grads = [p.grad._value for p in params]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type))
                for g in grads), 1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(total, 1e-12))
    for p in params:
        p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    return Tensor(total)


def clip_by_norm(x, max_norm, name=None):
    from ..tensor._helper import apply

    def f(v):
        n = jnp.sqrt(jnp.sum(jnp.square(v)))
        return jnp.where(n > max_norm, v * (max_norm / n), v)

    return apply(f, x, name="clip_by_norm")


def clip_by_global_norm(t_list, clip_norm, name=None):
    from ..tensor._helper import apply

    vals = [t._value for t in t_list]
    gn = _global_norm(vals)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    return [apply(lambda v: v * scale, t, name="clip_by_global_norm")
            for t in t_list], Tensor(gn)
