"""RNG state management.

TPU-native analogue of the reference Generator / seed plumbing
(reference: paddle/fluid/framework/generator.cc, python paddle.seed).

JAX RNG is functional (explicit keys); the dygraph layer needs stateful
semantics (`paddle.seed`, dropout without a key argument), so we keep a
global counter-based key chain: each draw splits off the chain
deterministically. Under jit (functional path) callers pass explicit keys.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful RNG: a root key plus a monotone counter."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Return a fresh jax PRNG key, advancing the stream."""
        with self._lock:
            c = self._count
            self._count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), c)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = int(state[0]), int(state[1])


_default_generator = Generator(0)
_numpy_generator = np.random.RandomState(0)

# --- functional key scope ---------------------------------------------------
# Under jax.jit tracing the global stateful generator would bake a constant
# key into the compiled program; instead the functional entry points
# (static.functional_call, hapi train step) push an explicit traced key here
# and stateless ops (dropout etc.) derive per-call subkeys from it.
import contextlib as _contextlib
import threading as _threading

_scope_state = _threading.local()


@_contextlib.contextmanager
def key_scope(key):
    """Make `key` the source of randomness for ops executed inside."""
    prev = getattr(_scope_state, "stack", None)
    if prev is None:
        _scope_state.stack = []
    _scope_state.stack.append([key, 0])
    try:
        yield
    finally:
        _scope_state.stack.pop()


def in_key_scope() -> bool:
    stack = getattr(_scope_state, "stack", None)
    return bool(stack)


def scope_key():
    """Next subkey from the innermost functional scope (traced-safe)."""
    stack = _scope_state.stack
    entry = stack[-1]
    k = jax.random.fold_in(entry[0], entry[1])
    entry[1] += 1
    return k


def op_key():
    """Key for a stateless-random op: functional scope if active, else the
    global stateful generator."""
    if in_key_scope():
        return scope_key()
    return next_key()


# host-side sampling streams (detection target sampling, NCE/sampled
# softmax) that must follow the global seed, like the reference engine
# RNG. Modules register their RandomState at import time.
_registered_sample_rngs: list = []


def register_sample_rng(rng) -> None:
    """Register a host numpy RandomState to be reseeded by paddle.seed."""
    _registered_sample_rngs.append(rng)


def seed(value: int) -> Generator:
    """paddle.seed equivalent: reseed the global generator (and numpy helper)."""
    _default_generator.manual_seed(value)
    _numpy_generator.seed(value % (2**32))
    for rng in _registered_sample_rngs:
        rng.seed(value % (2**32))
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
