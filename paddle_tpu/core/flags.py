"""Global flag/config registry.

TPU-native analogue of the reference's gflags tier
(reference: paddle/fluid/platform/flags.cc:33-359 and
pybind/global_value_getter_setter.cc): a typed, env-overridable registry
exposed through paddle-style ``set_flags``/``get_flags``.

Flags whose reference counterparts are CUDA-allocator knobs either map to the
XLA/TPU equivalent (documented per-flag) or exist for API compatibility.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    ctor: Callable[[str], Any]
    value: Any = None
    on_set: Optional[Callable[[Any], None]] = None


_REGISTRY: Dict[str, _Flag] = {}


def _parse_bool(s: str) -> bool:
    return str(s).lower() in ("1", "true", "yes", "on")


def define_flag(name, default, help="", ctor=None, on_set=None):
    if ctor is None:
        if isinstance(default, bool):
            ctor = _parse_bool
        elif isinstance(default, int):
            ctor = int
        elif isinstance(default, float):
            ctor = float
        else:
            ctor = str
    env = os.environ.get("FLAGS_" + name)
    value = ctor(env) if env is not None else default
    _REGISTRY[name] = _Flag(name, default, help, ctor, value, on_set)
    return value


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags equivalent (reference global_value_getter_setter.cc)."""
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _REGISTRY:
            raise KeyError(f"Unknown flag: {k}")
        f = _REGISTRY[key]
        f.value = f.ctor(v) if isinstance(v, str) else v
        if f.on_set is not None:
            f.on_set(f.value)


def get_flags(flags):
    """paddle.get_flags equivalent. Accepts a name or list of names."""
    if isinstance(flags, str):
        key = flags[6:] if flags.startswith("FLAGS_") else flags
        return _REGISTRY[key].value
    return {k: get_flags(k) for k in flags}


def all_flags():
    return {k: f.value for k, f in _REGISTRY.items()}


# ---------------------------------------------------------------------------
# Core flags (reference: platform/flags.cc). TPU mapping noted where relevant.
# ---------------------------------------------------------------------------
define_flag("default_dtype", "float32", "default floating dtype for tensor creation")
define_flag("check_nan_inf", False,
            "scan op outputs for nan/inf in eager mode (flags.cc:33 FLAGS_check_nan_inf)")
define_flag("benchmark", False,
            "block_until_ready after each eager op (flags.cc FLAGS_benchmark sync)")
define_flag("enable_unused_var_check", False,
            "warn for trainable params backward never reached "
            "(framework/unused_var_check.cc analogue at the tape level)")
define_flag("seed", 0, "global random seed")
define_flag("use_bf16_matmul", True,
            "allow bf16 matmul accumulation policy on TPU MXU")
define_flag("eager_delete_tensor_gb", 0.0,
            "compat: XLA manages memory; retained for API parity")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "compat: maps to XLA_PYTHON_CLIENT_MEM_FRACTION")
define_flag("allocator_strategy", "auto_growth",
            "compat: device memory is managed by the XLA runtime BFC allocator")
define_flag("cudnn_deterministic", False,
            "deterministic mode: on TPU, XLA is deterministic by construction")
define_flag("paddle_num_threads", 1, "host threads for data pipeline")
define_flag("print_op_summary", False, "print per-op timing summary at exit")
