"""ctypes loader for the native runtime library (native/ — C++).

The reference exposes its C++ core through a pybind11 module (reference:
paddle/fluid/pybind/pybind.cc, SURVEY.md §2 N38); here the native surface
is a minimal C ABI loaded with ctypes — no build-time Python dependency,
and the library is compiled on demand from native/ with the system
toolchain. Everything degrades gracefully: callers check ``available()``
and fall back to pure-Python paths.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SO = os.path.join(_REPO, "paddle_tpu", "_native", "libptl_runtime.so")
_SRC = os.path.join(_REPO, "native")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-s"], cwd=_SRC, capture_output=True,
                           timeout=300)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def _newer_than_lib(path: str) -> bool:
    try:
        return os.path.getmtime(path) > os.path.getmtime(_SO)
    except OSError:
        return False


def _sources_changed() -> bool:
    src_dir = os.path.join(_SRC, "src")
    try:
        names = os.listdir(src_dir)
    except OSError:
        return False
    return any(_newer_than_lib(os.path.join(src_dir, n)) for n in names)


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if _tried:
            return None
        _tried = True
        if (not os.path.exists(_SO) or _sources_changed()) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.ptl_version.restype = ctypes.c_int64
        lib.ptl_loader_create.restype = ctypes.c_void_p
        lib.ptl_loader_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int64]
        lib.ptl_loader_next.restype = ctypes.c_int
        lib.ptl_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64)]
        lib.ptl_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptl_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.ptl_writer_open.restype = ctypes.c_void_p
        lib.ptl_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ptl_writer_write.restype = ctypes.c_int
        lib.ptl_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_int64]
        lib.ptl_writer_close.restype = ctypes.c_int64
        lib.ptl_writer_close.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_uint32)]
        lib.ptl_crc32.restype = ctypes.c_uint32
        lib.ptl_crc32.argtypes = [ctypes.c_uint32, ctypes.c_void_p,
                                  ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


class AsyncWriter:
    """Background-thread file writer (native/src/file_writer.cc). Write
    calls return immediately; close() joins and returns (bytes, crc32)."""

    def __init__(self, path: str, depth: int = 8):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._res = None
        self._h = lib.ptl_writer_open(str(path).encode(), depth)
        if not self._h:
            raise OSError(f"cannot open {path} for writing")

    def write(self, data) -> None:
        buf = memoryview(data).cast("B")
        arr = (ctypes.c_char * len(buf)).from_buffer_copy(buf)
        if self._lib.ptl_writer_write(self._h, arr, len(buf)) != 0:
            raise OSError("native writer failed")

    def close(self):
        if self._h is None:
            if self._res is None:
                raise OSError("native writer IO error (earlier close failed)")
            return self._res
        crc = ctypes.c_uint32(0)
        total = self._lib.ptl_writer_close(self._h, ctypes.byref(crc))
        self._h = None
        if total < 0:
            raise OSError("native writer IO error on close")
        self._res = (int(total), int(crc.value))
        return self._res

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def crc32(data, crc: int = 0) -> int:
    """Rolling CRC32 matching the native writer's checksum. zlib's C
    implementation computes the identical polynomial, so use it directly
    (and it needs no native library)."""
    import zlib

    return zlib.crc32(memoryview(data).cast("B"), crc)
