"""Device-memory usage stats (reference: the RecordedCudaMallocHelper
per-device malloc accounting, platform/gpu_info.cc:461, and the
STAT_gpuN_mem_size monitor registry, platform/monitor.h:77 / monitor.cc:21).

TPU translation: XLA owns allocation, so accounting is READ from the
runtime (PjRt ``memory_stats``) instead of intercepted at malloc. The
paddle ``paddle.device.cuda.*`` accounting surface is kept with the same
semantics: current/peak bytes, per device.
"""
from __future__ import annotations

from typing import Optional

import jax

__all__ = ["memory_stats", "memory_allocated", "max_memory_allocated",
           "memory_reserved", "device_memory_summary"]


def _device(device_id: Optional[int] = None):
    devs = jax.local_devices()
    return devs[device_id or 0]


def memory_stats(device_id: Optional[int] = None) -> dict:
    """Raw PjRt memory stats for one local device ({} when the backend
    does not report — e.g. CPU)."""
    try:
        return dict(_device(device_id).memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device_id: Optional[int] = None) -> int:
    """Bytes currently held by live buffers (reference:
    paddle.device.cuda.memory_allocated)."""
    return int(memory_stats(device_id).get("bytes_in_use", 0))


def max_memory_allocated(device_id: Optional[int] = None) -> int:
    """High-water mark of bytes_in_use (reference:
    paddle.device.cuda.max_memory_allocated / RecordedCudaMallocHelper
    peak tracking)."""
    s = memory_stats(device_id)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device_id: Optional[int] = None) -> int:
    """Bytes reserved from the system by the allocator pool (reference:
    memory_reserved — the auto-growth allocator's pool size)."""
    s = memory_stats(device_id)
    return int(s.get("bytes_reserved", s.get("pool_bytes", 0)))


def device_memory_summary() -> str:
    """Human-readable per-device table (reference: the monitor stats
    printed by StatRegistry)."""
    lines = []
    for i, d in enumerate(jax.local_devices()):
        s = memory_stats(i)
        if not s:
            lines.append(f"{d}: (backend reports no memory stats)")
            continue
        used = s.get("bytes_in_use", 0) / 2**20
        peak = s.get("peak_bytes_in_use", 0) / 2**20
        limit = s.get("bytes_limit", 0) / 2**20
        lines.append(f"{d}: in_use={used:.1f}MiB peak={peak:.1f}MiB "
                     f"limit={limit:.1f}MiB")
    return "\n".join(lines)
