"""Dtype registry.

TPU-native analogue of the reference's proto::VarType dtype enum
(reference: paddle/fluid/framework/framework.proto:91-141, data_type.h).
We expose paddle-style dtype names backed directly by numpy/jax dtypes;
bfloat16 is first-class since it is the TPU compute dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (jax dtypes are numpy dtypes; bfloat16 comes from ml_dtypes).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGRAL = {uint8, int8, int16, int32, int64}


def convert_dtype(dtype):
    """Normalize a user-provided dtype (str / numpy / jax) to a numpy dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _ALIASES:
            return jnp.dtype(_ALIASES[key])
        return jnp.dtype(key)
    return jnp.dtype(dtype)


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


def get_default_dtype():
    from . import flags

    return convert_dtype(flags.get_flags("default_dtype"))


def set_default_dtype(dtype):
    from . import flags

    d = convert_dtype(dtype)
    if not (jnp.issubdtype(d, jnp.floating)):
        raise TypeError(
            "set_default_dtype only supports floating dtypes, got %s" % d)
    flags.set_flags({"default_dtype": str(d)})


def promote_types(a, b):
    return np.promote_types(convert_dtype(a), convert_dtype(b))
