"""Device abstraction.

TPU-native analogue of Place / DeviceContext / DeviceContextPool
(reference: paddle/fluid/platform/place.h:26-103, device_context.h:104-691).

On TPU there are no per-device user streams or vendor handles — XLA owns the
execution stream — so a Place is simply an identity wrapper over a
``jax.Device`` plus helpers to pick the current device. The DeviceContextPool
collapses into jax's device list.
"""
from __future__ import annotations

from typing import Optional

import jax


class Place:
    """Device identity (reference place.h Place tagged union)."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self.device_type] or \
            jax.devices()
        return devs[self._device_id % len(devs)]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._device_id == other._device_id)

    def __hash__(self):
        return hash((type(self).__name__, self._device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self._device_id})"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    """The accelerator place (reference CUDAPlace, place.h:37)."""

    device_type = "tpu"


# Alias so code written against the reference API keeps working.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    """Compat: host-pinned memory is managed by the XLA transfer manager."""


_expected_place: Optional[Place] = None


def target_platform() -> str:
    """Platform the current computation is being COMPILED FOR — not the
    process's default backend. AOT lowering against a TPU topology
    (jax.experimental.topologies) happens in a CPU-only process; the
    CPU-backend workarounds (bf16-collective promotion, pallas interpret
    mode) must key off the target, or the AOT artifact would bake the
    workarounds into the TPU program. Overridden by
    PADDLE_TPU_TARGET_PLATFORM; defaults to jax.default_backend()."""
    import os

    forced = os.environ.get("PADDLE_TPU_TARGET_PLATFORM")
    if forced:
        return forced
    return jax.default_backend()


def device_count() -> int:
    """Number of local accelerator devices (reference gpu_info GetCUDADeviceCount)."""
    return len([d for d in jax.devices() if d.platform != "cpu"]) or 0


def is_compiled_with_tpu() -> bool:
    return device_count() > 0


# Reference API names kept for switchers.
is_compiled_with_cuda = is_compiled_with_tpu


def set_device(device) -> Place:
    """paddle.set_device: 'tpu', 'tpu:0', 'cpu'."""
    global _expected_place
    if isinstance(device, Place):
        _expected_place = device
        return _expected_place
    name = str(device).lower()
    if name.startswith("cpu"):
        _expected_place = CPUPlace()
    else:
        idx = int(name.split(":")[1]) if ":" in name else 0
        _expected_place = TPUPlace(idx)
    return _expected_place


def get_device() -> str:
    p = expected_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"tpu:{p.get_device_id()}"


def expected_place() -> Place:
    global _expected_place
    if _expected_place is None:
        _expected_place = TPUPlace(0) if device_count() > 0 else CPUPlace()
    return _expected_place
