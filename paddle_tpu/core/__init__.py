"""Core runtime: device abstraction, dtypes, flags, errors, rng, profiler.

TPU-native analogue of the reference L0 platform layer
(reference: paddle/fluid/platform/)."""
from . import dtype, errors, flags, memory, place, profiler, rng  # noqa: F401
from .dtype import (bfloat16, bool_, complex64, complex128,  # noqa: F401
                    convert_dtype, float16, float32, float64,
                    get_default_dtype, int8, int16, int32, int64,
                    set_default_dtype, uint8)
from .errors import EnforceNotMet, enforce  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, Place,  # noqa: F401
                    TPUPlace, XPUPlace, device_count, expected_place,
                    get_device, is_compiled_with_cuda, is_compiled_with_tpu,
                    set_device)
from .rng import default_generator, get_rng_state, seed, set_rng_state  # noqa: F401
