"""Profiling spans.

TPU-native analogue of RecordEvent / EnableProfiler
(reference: paddle/fluid/platform/profiler.h:127,210, profiler.proto).

Host spans are recorded in-process (start/stop/summary table, chrome-trace
export); device truth comes from jax.profiler (XLA trace), which replaces the
reference's CUPTI DeviceTracer (device_tracer.h:43). RecordEvent doubles as a
jax.profiler.TraceAnnotation so spans show up inside XLA traces too.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional

import jax

_enabled = False
_lock = threading.Lock()
_events: List[tuple] = []  # (name, start_ns, end_ns, thread_id)
_jax_trace_dir: Optional[str] = None


class RecordEvent:
    """RAII span (reference profiler.h:127). Usable as context manager."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0
        self._jax_ctx = None

    def begin(self):
        if _enabled:
            self._t0 = time.perf_counter_ns()
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        return self

    def end(self):
        if _enabled and self._t0:
            t1 = time.perf_counter_ns()
            with _lock:
                _events.append(
                    (self.name, self._t0, t1, threading.get_ident()))
            if self._jax_ctx is not None:
                self._jax_ctx.__exit__(None, None, None)
                self._jax_ctx = None
            self._t0 = 0

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False


def enable_profiler(trace_dir: Optional[str] = None):
    """Start profiling (reference EnableProfiler profiler.h:210). If trace_dir
    is given, also starts a jax/XLA device trace into it."""
    global _enabled, _jax_trace_dir
    with _lock:
        _events.clear()
    _enabled = True
    if trace_dir:
        _jax_trace_dir = trace_dir
        jax.profiler.start_trace(trace_dir)


def disable_profiler(sorted_key: str = "total") -> str:
    """Stop profiling and return the formatted summary table."""
    global _enabled, _jax_trace_dir
    _enabled = False
    if _jax_trace_dir:
        jax.profiler.stop_trace()
        _jax_trace_dir = None
    return summary(sorted_key)


def reset_profiler():
    with _lock:
        _events.clear()


def summary(sorted_key: str = "total") -> str:
    stats: Dict[str, List[float]] = defaultdict(list)
    with _lock:
        for name, t0, t1, _tid in _events:
            stats[name].append((t1 - t0) / 1e6)
    rows = []
    for name, times in stats.items():
        rows.append((name, len(times), sum(times), sum(times) / len(times),
                     max(times), min(times)))
    key_idx = {"total": 2, "calls": 1, "ave": 3, "max": 4, "min": 5}.get(
        sorted_key, 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
             f"{'Max(ms)':>10}{'Min(ms)':>10}"]
    for r in rows:
        lines.append(f"{r[0]:<40}{r[1]:>8}{r[2]:>12.3f}{r[3]:>10.3f}"
                     f"{r[4]:>10.3f}{r[5]:>10.3f}")
    return "\n".join(lines)


def export_chrome_tracing(path: str):
    """Write collected host spans as a chrome://tracing JSON file
    (reference profiler chrome-trace via profiler.proto)."""
    with _lock:
        evs = list(_events)
    trace = {"traceEvents": [
        {"name": n, "ph": "X", "ts": t0 / 1e3, "dur": (t1 - t0) / 1e3,
         "pid": 0, "tid": tid, "cat": "host"}
        for n, t0, t1, tid in evs]}
    with open(path, "w") as f:
        json.dump(trace, f)


@contextmanager
def profiler(state: str = "All", tracer_option: str = "Default",
             profile_path: Optional[str] = None):
    """paddle.fluid.profiler context-manager equivalent."""
    enable_profiler()
    try:
        yield
    finally:
        table = disable_profiler()
        if profile_path:
            with open(profile_path, "w") as f:
                f.write(table)
        else:
            print(table)
