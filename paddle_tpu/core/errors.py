"""Typed error machinery.

TPU-native analogue of PADDLE_ENFORCE_* + platform::errors
(reference: paddle/fluid/platform/enforce.h, errors.cc, error_codes.proto).
On TPU the Python layer is the host control plane, so these are plain Python
exceptions with the same taxonomy; `enforce` raises with a captured message.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base error — reference enforce.h:EnforceNotMet."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet, PermissionError):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExternalError(EnforceNotMet):
    pass


def enforce(cond, msg="", exc=InvalidArgumentError, *fmt_args):
    """PADDLE_ENFORCE equivalent: raise `exc` with `msg` when cond is false."""
    if not cond:
        raise exc(msg % fmt_args if fmt_args else msg)


def enforce_eq(a, b, msg="", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"Expected {a} == {b}. {msg}")


def enforce_gt(a, b, msg="", exc=InvalidArgumentError):
    if not a > b:
        raise exc(f"Expected {a} > {b}. {msg}")


def enforce_ge(a, b, msg="", exc=InvalidArgumentError):
    if not a >= b:
        raise exc(f"Expected {a} >= {b}. {msg}")


def enforce_not_none(x, msg="", exc=NotFoundError):
    if x is None:
        raise exc(msg or "Expected value to be not None.")
    return x
