"""Weight-decay regularizers (reference: python/paddle/regularizer.py —
L1Decay/L2Decay, applied per-param via ParamAttr or globally via the
optimizer's weight_decay argument).

TPU-native application: instead of the reference's appended decay ops in
the program (fluid regularizer append_regularization_ops), the decay
folds into the fused optimizer update — pass an instance as
``weight_decay=`` to any optimizer, or attach via ParamAttr(
regularizer=...) for per-param override.
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class _Decay:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L2Decay(_Decay):
    """grad += coeff * param (reference regularizer.py L2Decay)."""

    def grad_term(self, param_value):
        return self.coeff * param_value


class L1Decay(_Decay):
    """grad += coeff * sign(param) (reference regularizer.py L1Decay)."""

    def grad_term(self, param_value):
        import jax.numpy as jnp

        return self.coeff * jnp.sign(param_value)
