"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """reference: metric/metrics.py Accuracy (accuracy_op)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = np.asarray(pred)
        label = np.asarray(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.argmax(-1) if label.shape[-1] == pred.shape[-1] \
                else label.squeeze(-1)
        correct = (idx == label[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        correct = np.asarray(correct)
        num = correct.shape[0]
        accs = []
        for k in self.topk:
            c = correct[..., :k].any(-1).sum()
            self.total[self.topk.index(k)] += int(c)
            self.count[self.topk.index(k)] += num
            accs.append(c / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds).round().reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds).round().reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    """reference: metric/metrics.py Auc (auc_op histogram approximation)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bins = np.clip((pos_prob * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """functional accuracy (reference: metric/metrics.py accuracy)."""
    pred = np.asarray(input)
    lbl = np.asarray(label).reshape(-1)
    topk_idx = np.argsort(-pred, axis=-1)[:, :k]
    acc = (topk_idx == lbl[:, None]).any(-1).mean()
    return Tensor(np.asarray(acc, np.float32))
