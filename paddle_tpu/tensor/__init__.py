"""paddle.tensor-equivalent op library.

Aggregates all op submodules and installs them as ``Tensor`` methods plus the
arithmetic dunder operators — the TPU-native replacement for the reference's
monkey-patched math-op methods (reference: python/paddle/fluid/dygraph/
math_op_patch.py and python/paddle/tensor/__init__.py).
"""
from __future__ import annotations

from .array import array_length, array_read, array_write, create_array  # noqa: F401

from ..framework.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from . import creation, linalg, logic, manipulation, math, random, search, stat
from .creation import *  # noqa: F401,F403
from .linalg import (cholesky, cholesky_solve, cond, corrcoef, cov,  # noqa: F401
                     cross, det, dist, eig, eigh, eigvals, eigvalsh, inverse,
                     lstsq, matrix_power, matrix_rank, multi_dot, norm, pinv,
                     qr, slogdet, solve, svd, triangular_solve)
from .logic import (allclose, bitwise_and, bitwise_not, bitwise_or,  # noqa: F401
                    bitwise_xor, equal, equal_all, greater_equal, greater_than,
                    is_empty, is_tensor, isclose, isin, less_equal, less_than,
                    logical_and, logical_not, logical_or, logical_xor,
                    not_equal)
from .manipulation import (broadcast_shape, broadcast_tensors,  # noqa: F401
                           broadcast_to, cast,
                           chunk, concat, crop, expand, expand_as, flatten,
                           flip, gather, gather_nd, index_sample, index_select,
                           masked_fill, masked_select, moveaxis,
                           put_along_axis, repeat_interleave, reshape,
                           reshape_, roll, rot90, scatter, scatter_,
                           scatter_nd, scatter_nd_add, shard_index, slice,
                           rank, reverse, shape, split, squeeze, squeeze_,
                           stack, strided_slice, swapaxes, t, unstack,
                           unsqueeze_,
                           take_along_axis, tile, transpose, unbind, unique,
                           unique_consecutive, unsqueeze, where)
from .math import *  # noqa: F401,F403
from .random import (bernoulli, exponential_, gaussian, multinomial,  # noqa: F401
                     normal, normal_, poisson, rand, randint, randint_like,
                     randn, randperm, shuffle, standard_normal, uniform,
                     uniform_)
from .search import (argmax, argmin, argsort, bucketize, kthvalue,  # noqa: F401
                     masked_select, mode, nonzero, searchsorted, sort, topk)
from .stat import (bincount, histogram, median, nanmedian, numel,  # noqa: F401
                   quantile, std, var)

# ---------------------------------------------------------------------------
# Install tensor methods
# ---------------------------------------------------------------------------
_METHOD_SOURCES = [math, manipulation, logic, search, stat, linalg, creation,
                   random]
_SKIP = {"apply", "unwrap", "wrap", "axis_arg", "shape_arg", "make_unary",
         "make_binary", "to_tensor"}


def _install_methods():
    import types

    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not isinstance(fn, types.FunctionType):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    import operator as _op  # noqa: F401

    def _binop(fn, swap=False):
        def method(self, other):
            if swap:
                return fn(other if isinstance(other, Tensor)
                          else Tensor(other, dtype=None), self)
            return fn(self, other)

        return method

    Tensor.__add__ = _binop(math.add)
    Tensor.__radd__ = _binop(math.add, swap=True)
    Tensor.__sub__ = _binop(math.subtract)
    Tensor.__rsub__ = _binop(math.subtract, swap=True)
    Tensor.__mul__ = _binop(math.multiply)
    Tensor.__rmul__ = _binop(math.multiply, swap=True)
    Tensor.__truediv__ = _binop(math.divide)
    Tensor.__rtruediv__ = _binop(math.divide, swap=True)
    Tensor.__floordiv__ = _binop(math.floor_divide)
    Tensor.__rfloordiv__ = _binop(math.floor_divide, swap=True)
    Tensor.__mod__ = _binop(math.remainder)
    Tensor.__pow__ = _binop(math.pow)
    Tensor.__rpow__ = _binop(math.pow, swap=True)
    Tensor.__matmul__ = _binop(math.matmul)
    Tensor.__rmatmul__ = _binop(math.matmul, swap=True)
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__eq__ = _binop(logic.equal)
    Tensor.__ne__ = _binop(logic.not_equal)
    Tensor.__lt__ = _binop(logic.less_than)
    Tensor.__le__ = _binop(logic.less_equal)
    Tensor.__gt__ = _binop(logic.greater_than)
    Tensor.__ge__ = _binop(logic.greater_equal)
    Tensor.__hash__ = object.__hash__  # __eq__ override would kill hashing
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__and__ = _binop(logic.logical_and)
    Tensor.__or__ = _binop(logic.logical_or)
    Tensor.__xor__ = _binop(logic.logical_xor)

    @property
    def T(self):  # noqa: N802
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    Tensor.T = T
    Tensor.exp_ = lambda self: self.set_value(math.exp(self.detach()))
    Tensor.sqrt_ = lambda self: self.set_value(math.sqrt(self.detach()))
    Tensor.clip_ = lambda self, lo=None, hi=None: self.set_value(
        math.clip(self.detach(), lo, hi))
    Tensor.mean_all = lambda self: stat.mean(self)


_install_methods()
del _install_methods
