"""Random sampling ops (reference: python/paddle/tensor/random.py; kernels
operators/uniform_random_op.cc, gaussian_random_op.cc …).

Eager mode draws from the global stateful Generator (core.rng); under jit the
functional layers take explicit keys. Sampling ops are non-differentiable
w.r.t. their (absent) tensor inputs, matching the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import rng
from ..framework.tensor import Tensor
from ._helper import shape_arg, unwrap


def _d(dtype, default=None):
    if dtype is None:
        return dtype_mod.convert_dtype(default) if default else \
            dtype_mod.get_default_dtype()
    return dtype_mod.convert_dtype(dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = jax.random.PRNGKey(seed) if seed else rng.next_key()
    return Tensor(jax.random.uniform(key, shape_arg(shape), _d(dtype),
                                     minval=unwrap(min), maxval=unwrap(max)))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        shape = np.broadcast_shapes(
            np.shape(unwrap(mean)), np.shape(unwrap(std)))
    out = jax.random.normal(rng.next_key(), shape_arg(shape or ()),
                            dtype_mod.get_default_dtype())
    return Tensor(out * unwrap(std) + unwrap(mean))


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    out = jax.random.normal(rng.next_key(), shape_arg(shape), _d(dtype))
    return Tensor(out * std + mean)


def randn(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(rng.next_key(), shape_arg(shape),
                                     int(low), int(high),
                                     _d(dtype, "int64")))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    v = unwrap(x)
    return randint(low, high, v.shape, dtype or v.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(rng.next_key(), int(n)).astype(
        dtype_mod.convert_dtype(dtype)))


def shuffle(x, name=None):
    return Tensor(jax.random.permutation(rng.next_key(), unwrap(x), axis=0))


def multinomial(x, num_samples=1, replacement=False, name=None):
    v = unwrap(x)
    logits = jnp.log(jnp.clip(v, 1e-30, None))
    if replacement:
        out = jax.random.categorical(rng.next_key(), logits,
                                     shape=v.shape[:-1] + (num_samples,))
    else:
        # Gumbel top-k trick for sampling without replacement.
        g = jax.random.gumbel(rng.next_key(), v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    v = unwrap(x)
    return Tensor(jax.random.bernoulli(rng.next_key(), v, v.shape).astype(
        v.dtype))


def poisson(x, name=None):
    v = unwrap(x)
    return Tensor(jax.random.poisson(rng.next_key(), v, v.shape).astype(
        v.dtype))


def exponential_(x, lam=1.0, name=None):
    v = unwrap(x)
    x._value = jax.random.exponential(rng.next_key(), v.shape, v.dtype) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x._value = unwrap(uniform(x.shape, x.dtype, min, max, seed))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = unwrap(gaussian(x.shape, mean, std, x.dtype))
    return x
