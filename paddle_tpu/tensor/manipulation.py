"""Shape / layout manipulation ops
(reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ._helper import apply, axis_arg, shape_arg, unwrap


def reshape(x, shape, name=None):
    s = shape_arg(shape)
    return apply(lambda v: jnp.reshape(v, s), x, name="reshape")


def reshape_(x, shape, name=None):
    x._value = jnp.reshape(x._value, shape_arg(shape))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(v):
        nd = v.ndim
        a = start_axis % nd if nd else 0
        b = stop_axis % nd if nd else 0
        new_shape = v.shape[:a] + (-1,) + v.shape[b + 1:]
        return jnp.reshape(v, new_shape)

    return apply(f, x, name="flatten")


def squeeze(x, axis=None, name=None):
    return apply(lambda v: jnp.squeeze(v, axis_arg(axis)), x, name="squeeze")


def unsqueeze(x, axis, name=None):
    return apply(lambda v: jnp.expand_dims(v, axis_arg(axis)), x,
                 name="unsqueeze")


def transpose(x, perm=None, name=None):
    return apply(lambda v: jnp.transpose(v, perm), x, name="transpose")


def t(x, name=None):
    return apply(lambda v: v.T, x, name="t")


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), x,
                 name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis0, axis1), x, name="swapaxes")


def concat(x, axis=0, name=None):
    axis = int(unwrap(axis)) if not isinstance(axis, int) else axis
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *x, name="concat")


def stack(x, axis=0, name=None):
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *x, name="stack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis)) if not isinstance(axis, int) else axis
    if isinstance(num_or_sections, int):
        n = num_or_sections
        return list(apply(lambda v: tuple(jnp.split(v, n, axis=axis)), x,
                          name="split"))
    secs = [int(unwrap(s)) for s in num_or_sections]
    dim = x.shape[axis]
    secs = [dim - sum(s for s in secs if s >= 0) if s < 0 else s for s in secs]
    offsets = np.cumsum([0] + secs[:-1]).tolist()

    def f(v):
        return tuple(jnp.take(v, jnp.arange(o, o + s), axis=axis)
                     for o, s in zip(offsets, secs))

    return list(apply(f, x, name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    return list(apply(
        lambda v: tuple(jnp.take(v, i, axis=axis) for i in range(n)),
        x, name="unbind"))


def tile(x, repeat_times, name=None):
    reps = shape_arg(repeat_times)
    return apply(lambda v: jnp.tile(v, reps), x, name="tile")


def expand(x, shape, name=None):
    s = shape_arg(shape)

    def f(v):
        tgt = tuple(v.shape[i - (len(s) - v.ndim)] if d == -1 else d
                    for i, d in enumerate(s))
        return jnp.broadcast_to(v, tgt)

    return apply(f, x, name="expand")


broadcast_to = expand


def expand_as(x, y, name=None):
    tgt = tuple(y.shape)
    return apply(lambda v: jnp.broadcast_to(v, tgt), x, name="expand_as")


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    tgt = np.broadcast_shapes(*shapes)
    return [apply(lambda v: jnp.broadcast_to(v, tgt), t,
                  name="broadcast_tensors") for t in inputs]


def flip(x, axis, name=None):
    return apply(lambda v: jnp.flip(v, axis_arg(axis)), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k, axes), x, name="rot90")


def roll(x, shifts, axis=None, name=None):
    return apply(lambda v: jnp.roll(v, shifts, axis_arg(axis)), x, name="roll")


def gather(x, index, axis=0, name=None):
    axis = int(unwrap(axis)) if not isinstance(axis, int) else axis
    return apply(lambda v, i: jnp.take(v, i.reshape(-1), axis=axis), x, index,
                 name="gather")


def gather_nd(x, index, name=None):
    def f(v, idx):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return v[comps]

    return apply(f, x, index, name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].add(u)

    return apply(f, x, index, updates, name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    x._value = scatter(x.detach(), index, updates, overwrite)._value
    return x


def scatter_nd_add(x, index, updates, name=None):
    def f(v, idx, u):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return v.at[comps].add(u)

    return apply(f, x, index, updates, name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    return scatter_nd_add(zeros(shape, dtype=updates.dtype), index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i.reshape(-1), axis=axis), x, index,
                 name="index_select")


def index_sample(x, index, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index,
                 name="index_sample")


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr,
                 indices, name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    def f(v, i, u):
        u = jnp.broadcast_to(u, i.shape).astype(v.dtype)
        dims = [jnp.arange(s).reshape([-1 if k == d else 1
                                       for k in range(v.ndim)])
                for d, s in enumerate(i.shape)]
        comps = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape)
                      for d in range(v.ndim))
        if reduce == "add":
            return v.at[comps].add(u)
        if reduce == "multiply" or reduce == "mul":
            return v.at[comps].multiply(u)
        return v.at[comps].set(u)

    return apply(f, arr, indices, values, name="put_along_axis")


def masked_select(x, mask, name=None):
    # Dynamic output shape: eager-only (like reference op, masked_select_op.cc).
    return Tensor(unwrap(x)[np.asarray(unwrap(mask))],
                  stop_gradient=True)


def masked_fill(x, mask, value, name=None):
    return apply(lambda v, m: jnp.where(m, jnp.asarray(value, v.dtype), v),
                 x, mask, name="masked_fill")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                 name="where")


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """reference: operators/slice_op.cc"""
    import builtins

    sl = [builtins.slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        sl[int(a)] = builtins.slice(int(unwrap(s)), int(unwrap(e)))
    sl = tuple(sl)
    return apply(lambda v: v[sl], x, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    sl = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        import builtins

        sl[a] = builtins.slice(int(unwrap(s)), int(unwrap(e)), int(unwrap(st)))
    return apply(lambda v: v[tuple(sl)], x, name="strided_slice")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Dynamic output shape → eager numpy path (reference unique_op.cc is also
    # host-synchronous for the count).
    res = np.unique(np.asarray(unwrap(x)), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(Tensor(r) for r in res)
    return Tensor(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0], bool)
    keep[1:] = np.any(
        arr[1:].reshape(arr.shape[0] - 1, -1) !=
        arr[:-1].reshape(arr.shape[0] - 1, -1), axis=1)
    out = [Tensor(arr[keep])]
    if return_inverse:
        out.append(Tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        out.append(Tensor(np.diff(np.append(idx, arr.shape[0]))))
    return out[0] if len(out) == 1 else tuple(out)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = np.asarray(unwrap(repeats))
        total = int(repeats.sum())
        return apply(lambda v: jnp.repeat(v, jnp.asarray(repeats), axis=axis,
                                          total_repeat_length=total),
                     x, name="repeat_interleave")
    return apply(lambda v: jnp.repeat(v, repeats, axis=axis), x,
                 name="repeat_interleave")


def cast(x, dtype):
    return x.astype(dtype)


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    s = shape_arg(shape)
    offs = [0] * len(s) if offsets is None else \
        [int(unwrap(o)) for o in offsets]
    sl = tuple(builtins.slice(o, o + d) for o, d in zip(offs, s))
    return apply(lambda v: v[sl], x, name="crop")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,  # noqa: A002
                name=None):
    """reference: operators/shard_index_op.cc (PS sharded embedding helper)."""
    def f(v):
        size = index_num // nshards
        owner = v // size
        local = v % size
        return jnp.where(owner == shard_id, local, ignore_value)

    return apply(f, input, differentiable=False, name="shard_index")


def unstack(x, axis=0, num=None, name=None):
    """Split ``x`` into a python list along ``axis`` (reference:
    fluid/layers/nn.py unstack → unstack_op.cc). Static shapes make
    ``num`` redundant; accepted for API parity."""
    n = x.shape[axis] if num is None else num
    return [squeeze(s, axis=axis) for s in split(x, n, axis=axis)]


def reverse(x, axis, name=None):
    """Legacy alias of flip (reference: fluid/layers/nn.py reverse)."""
    return flip(x, axis)


def broadcast_shape(x_shape, y_shape):
    """Result shape of broadcasting two shapes (reference:
    paddle.broadcast_shape)."""
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(int(v) for v in x_shape),
                                     tuple(int(v) for v in y_shape)))


def rank(input, name=None):  # noqa: A002
    """0-D int32 tensor holding ndim (reference: fluid/layers/nn.py
    rank)."""
    from ..framework.tensor import Tensor

    return Tensor(jnp.asarray(len(unwrap(input).shape), jnp.int32))


def shape(input, name=None):  # noqa: A002
    """1-D int32 tensor of the (static) shape — the reference's shape op
    (operators/shape_op.cc) reads it at runtime; XLA shapes are static
    so this is a constant."""
    from ..framework.tensor import Tensor

    return Tensor(jnp.asarray(unwrap(input).shape, jnp.int32))


def squeeze_(x, axis=None, name=None):
    """Inplace squeeze (reference: paddle.squeeze_), differentiable via
    tape rebinding."""
    from ._helper import inplace_apply

    return inplace_apply(lambda v: jnp.squeeze(v, axis_arg(axis)), x,
                         name="squeeze_")


def unsqueeze_(x, axis, name=None):
    """Inplace unsqueeze (reference: paddle.unsqueeze_), differentiable via
    tape rebinding."""
    from ._helper import inplace_apply

    return inplace_apply(lambda v: jnp.expand_dims(v, axis_arg(axis)), x,
                         name="unsqueeze_")
