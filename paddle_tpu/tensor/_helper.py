"""Shared plumbing for the tensor op library.

Every public op routes through the eager tape (``autograd.tape.apply``) so it
is differentiable and also traceable under jax.jit. This single entry point is
the TPU-native replacement for the reference's generated ``core.ops.*``
fast-path functions (reference: paddle/fluid/pybind/op_function_generator.cc).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.tape import apply as _apply
from ..framework.tensor import Tensor


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def wrap(v, stop_gradient=True) -> Tensor:
    return Tensor(v, stop_gradient=stop_gradient)


def apply(fn, *args, **kwargs):
    return _apply(fn, *args, **kwargs)


def axis_arg(axis):
    """Normalize paddle axis arg (int | list | tuple | None) for jnp."""
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def shape_arg(shape):
    """Normalize a paddle shape arg (list of ints / Tensors, or Tensor)."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def make_unary(jnp_fn, opname):
    def op(x, name=None):
        return apply(jnp_fn, x, name=opname)

    op.__name__ = opname
    op.__doc__ = f"Elementwise {opname} (jnp.{getattr(jnp_fn, '__name__', opname)})."
    return op


def make_binary(jnp_fn, opname):
    def op(x, y, name=None):
        return apply(jnp_fn, x, y, name=opname)

    op.__name__ = opname
    op.__doc__ = f"Elementwise {opname} with numpy broadcasting."
    return op
