"""Shared plumbing for the tensor op library.

Every public op routes through the eager tape (``autograd.tape.apply``) so it
is differentiable and also traceable under jax.jit. This single entry point is
the TPU-native replacement for the reference's generated ``core.ops.*``
fast-path functions (reference: paddle/fluid/pybind/op_function_generator.cc).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.tape import apply as _apply
from ..framework.tensor import Tensor


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def wrap(v, stop_gradient=True) -> Tensor:
    return Tensor(v, stop_gradient=stop_gradient)


def apply(fn, *args, **kwargs):
    return _apply(fn, *args, **kwargs)


def axis_arg(axis):
    """Normalize paddle axis arg (int | list | tuple | None) for jnp."""
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def shape_arg(shape):
    """Normalize a paddle shape arg (list of ints / Tensors, or Tensor)."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def inplace_apply(fn, x, *extra, name=""):
    """Differentiable in-place op (``relu_``, ``squeeze_`` …).

    The reference's generated ``core.ops.<op>_`` fast paths are fully
    differentiable (pybind/op_function_generator.cc registers grad nodes for
    inplace variants). Mutating ``x._value`` alone would silently drop the
    op from the tape, so instead: snapshot ``x``'s pre-mutation state into a
    detached alias, run the op through the tape against the alias, then
    rebind ``x`` to the result *object state* in place. Downstream consumers
    of ``x`` see the new value and the new tape node; backward flows through
    the alias into ``x``'s original producer.
    """
    if not isinstance(x, Tensor):
        return wrap(fn(x, *(unwrap(e) for e in extra)))
    prev = Tensor.__new__(Tensor)
    prev.__dict__.update(x.__dict__)
    out = _apply(fn, prev, *extra, name=name)
    x._value = out._value
    x._node = out._node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def make_unary(jnp_fn, opname):
    def op(x, name=None):
        return apply(jnp_fn, x, name=opname)

    op.__name__ = opname
    op.__doc__ = f"Elementwise {opname} (jnp.{getattr(jnp_fn, '__name__', opname)})."
    return op


def make_binary(jnp_fn, opname):
    def op(x, y, name=None):
        return apply(jnp_fn, x, y, name=opname)

    op.__name__ = opname
    op.__doc__ = f"Elementwise {opname} with numpy broadcasting."
    return op
