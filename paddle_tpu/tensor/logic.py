"""Comparison / logic ops (reference: python/paddle/tensor/logic.py;
kernels operators/controlflow/compare_op.cc, logical_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ._helper import apply, unwrap


def _cmp(jnp_fn, opname):
    def op(x, y, name=None):
        return apply(jnp_fn, x, y, differentiable=False, name=opname)

    op.__name__ = opname
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return apply(jnp.logical_not, x, differentiable=False, name="logical_not")


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x, differentiable=False, name="bitwise_not")


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(unwrap(x), unwrap(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 x, y, differentiable=False, name="isclose")


def is_empty(x, name=None):
    return Tensor(np.asarray(int(np.prod(unwrap(x).shape)) == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(lambda a, b: jnp.isin(a, b, invert=invert), x, test_x,
                 differentiable=False, name="isin")
