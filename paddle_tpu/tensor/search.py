"""Search / sort ops (reference: python/paddle/tensor/search.py;
kernels operators/argsort_op.cc, top_k_v2_op.cc, where_index_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ._helper import apply, unwrap


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmax(v if axis is not None else v.reshape(-1),
                         axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.int64)

    return apply(f, x, differentiable=False, name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmin(v if axis is not None else v.reshape(-1), axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.int64)

    return apply(f, x, differentiable=False, name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def f(v):
        idx = jnp.argsort(-v if descending else v, axis=axis)
        return idx.astype(jnp.int64)

    return apply(f, x, differentiable=False, name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def f(v):
        out = jnp.sort(v, axis=axis)
        return jnp.flip(out, axis) if descending else out

    return apply(f, x, name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    k = int(unwrap(k))

    def f(v):
        ax = -1 if axis is None else int(axis)
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vv, k)
        else:
            vals, idx = jax.lax.top_k(-vv, k)
            vals = -vals
        idx = idx.astype(jnp.int64)
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = apply(f, x, name="topk")
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        vv = jnp.sort(v, axis=axis)
        ii = jnp.argsort(v, axis=axis).astype(jnp.int64)
        val = jnp.take(vv, k - 1, axis=axis)
        idx = jnp.take(ii, k - 1, axis=axis)
        if keepdim:
            val = jnp.expand_dims(val, axis)
            idx = jnp.expand_dims(idx, axis)
        return val, idx

    return apply(f, x, name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (ties → larger value, like reference
    mode_op which picks the last of sorted equals)."""
    arr = np.asarray(unwrap(x))
    mv = np.moveaxis(arr, axis, -1)
    flat = mv.reshape(-1, mv.shape[-1])
    vals = np.empty(flat.shape[0], arr.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.max(np.flatnonzero(row == best))
    out_shape = mv.shape[:-1]
    v_out, i_out = vals.reshape(out_shape), idxs.reshape(out_shape)
    if keepdim:
        v_out = np.expand_dims(v_out, axis)
        i_out = np.expand_dims(i_out, axis)
    return Tensor(v_out), Tensor(i_out)


def nonzero(x, as_tuple=False):
    # Dynamic shape → host-synchronous, like reference where_index_op.
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(n.reshape(-1, 1).astype(np.int64)) for n in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def where(condition, x=None, y=None, name=None):
    from .manipulation import where as _where

    return _where(condition, x, y, name)


def index_select(x, index, axis=0, name=None):
    from .manipulation import index_select as _is

    return _is(x, index, axis, name)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms

    return _ms(x, mask, name)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    return apply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(
        jnp.int32 if out_int32 else jnp.int64),
        sorted_sequence, values, differentiable=False, name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
