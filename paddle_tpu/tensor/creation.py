"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..framework.tensor import Tensor, to_tensor  # re-export to_tensor
from ._helper import apply, shape_arg, unwrap

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "arange", "linspace", "logspace", "eye", "empty",
    "empty_like", "meshgrid", "diag", "diagflat", "tril", "triu", "assign",
    "clone", "numel", "tolist", "one_hot",
]


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return dtype_mod.convert_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(shape_arg(shape),
                            _dt(dtype, dtype_mod.get_default_dtype())))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(shape_arg(shape),
                           _dt(dtype, dtype_mod.get_default_dtype())))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(shape_arg(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=_dt(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=_dt(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(unwrap(x), fill_value, dtype=_dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = unwrap(start)
    end = unwrap(end)
    step = unwrap(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(num),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(num),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype, dtype_mod.get_default_dtype())))


def meshgrid(*args, **kwargs):
    args = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) \
        else args
    outs = jnp.meshgrid(*[unwrap(a) for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None):
    def f(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, v.dtype)
            return base + jnp.diag(v - 0, offset) - \
                jnp.diag(jnp.full(v.shape, padding_value, v.dtype), offset)
        return jnp.diag(v, offset)

    return apply(f, x, name="diag")


def diagflat(x, offset=0, name=None):
    return apply(lambda v: jnp.diagflat(v, offset), x, name="diagflat")


def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, diagonal), x, name="triu")


def assign(x, output=None):
    """paddle.assign: copy input into a (new or given) tensor."""
    v = jnp.asarray(unwrap(x) if isinstance(x, Tensor) else np.asarray(x))
    if output is None:
        return apply(lambda a: a + 0, x if isinstance(x, Tensor) else Tensor(v),
                     name="assign")
    output.set_value(v)
    return output


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(unwrap(x).shape, dtype=np.int64))))


def tolist(x):
    return x.tolist()


def one_hot(x, num_classes, name=None):
    return apply(
        lambda v: jnp.eye(int(num_classes),
                          dtype=dtype_mod.get_default_dtype())[v],
        x, differentiable=False, name="one_hot")
