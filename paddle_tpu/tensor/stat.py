"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ._helper import apply, axis_arg, unwrap


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=axis_arg(axis), keepdims=keepdim),
                 x, name="mean")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.std(v, axis=axis_arg(axis), keepdims=keepdim,
                                   ddof=1 if unbiased else 0), x, name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.var(v, axis=axis_arg(axis), keepdims=keepdim,
                                   ddof=1 if unbiased else 0), x, name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(v):
        if mode == "min":
            # lower median
            vv = jnp.sort(v if axis is not None else v.reshape(-1),
                          axis=axis if axis is not None else 0)
            n = vv.shape[axis if axis is not None else 0]
            return jnp.take(vv, (n - 1) // 2, axis=axis if axis is not None else 0)
        return jnp.median(v, axis=axis_arg(axis), keepdims=keepdim)

    return apply(f, x, name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmedian(v, axis=axis_arg(axis),
                                         keepdims=keepdim), x, name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda v: jnp.quantile(v, jnp.asarray(unwrap(q)),
                                        axis=axis_arg(axis), keepdims=keepdim,
                                        method=interpolation),
                 x, name="quantile")


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    arr = np.asarray(unwrap(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    if weights is None:
        return Tensor(np.bincount(np.asarray(unwrap(x)).reshape(-1),
                                  minlength=minlength))
    return Tensor(np.bincount(np.asarray(unwrap(x)).reshape(-1),
                              np.asarray(unwrap(weights)).reshape(-1),
                              minlength=minlength))


def numel(x, name=None):
    return Tensor(np.asarray(int(np.prod(unwrap(x).shape, dtype=np.int64))))
