"""Math / reduction ops (reference: python/paddle/tensor/math.py; kernels in
paddle/fluid/operators/elementwise/, reduce_ops/, math/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ._helper import apply, axis_arg, make_binary, make_unary, unwrap

# -- elementwise unary ------------------------------------------------------
exp = make_unary(jnp.exp, "exp")
expm1 = make_unary(jnp.expm1, "expm1")
log = make_unary(jnp.log, "log")
log2 = make_unary(jnp.log2, "log2")
log10 = make_unary(jnp.log10, "log10")
log1p = make_unary(jnp.log1p, "log1p")
sqrt = make_unary(jnp.sqrt, "sqrt")
rsqrt = make_unary(lambda x: jax.lax.rsqrt(x), "rsqrt")
square = make_unary(jnp.square, "square")
abs = make_unary(jnp.abs, "abs")  # noqa: A001
neg = make_unary(jnp.negative, "neg")
sign = make_unary(jnp.sign, "sign")
floor = make_unary(jnp.floor, "floor")
ceil = make_unary(jnp.ceil, "ceil")
round = make_unary(jnp.round, "round")  # noqa: A001
trunc = make_unary(jnp.trunc, "trunc")
frac = make_unary(lambda x: x - jnp.trunc(x), "frac")
sin = make_unary(jnp.sin, "sin")
cos = make_unary(jnp.cos, "cos")
tan = make_unary(jnp.tan, "tan")
asin = make_unary(jnp.arcsin, "asin")
acos = make_unary(jnp.arccos, "acos")
atan = make_unary(jnp.arctan, "atan")
sinh = make_unary(jnp.sinh, "sinh")
cosh = make_unary(jnp.cosh, "cosh")
tanh = make_unary(jnp.tanh, "tanh")
asinh = make_unary(jnp.arcsinh, "asinh")
acosh = make_unary(jnp.arccosh, "acosh")
atanh = make_unary(jnp.arctanh, "atanh")
reciprocal = make_unary(jnp.reciprocal, "reciprocal")
erf = make_unary(jax.scipy.special.erf, "erf")
erfinv = make_unary(jax.scipy.special.erfinv, "erfinv")
digamma = make_unary(jax.scipy.special.digamma, "digamma")
lgamma = make_unary(jax.scipy.special.gammaln, "lgamma")
angle = make_unary(jnp.angle, "angle")
conj = make_unary(jnp.conj, "conj")
real = make_unary(jnp.real, "real")
imag = make_unary(jnp.imag, "imag")

# -- elementwise binary -----------------------------------------------------
add = make_binary(jnp.add, "add")
subtract = make_binary(jnp.subtract, "subtract")
multiply = make_binary(jnp.multiply, "multiply")
divide = make_binary(jnp.true_divide, "divide")
floor_divide = make_binary(jnp.floor_divide, "floor_divide")
remainder = make_binary(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
pow = make_binary(jnp.power, "pow")  # noqa: A001
maximum = make_binary(jnp.maximum, "maximum")
minimum = make_binary(jnp.minimum, "minimum")
fmax = make_binary(jnp.fmax, "fmax")
fmin = make_binary(jnp.fmin, "fmin")
atan2 = make_binary(jnp.arctan2, "atan2")
hypot = make_binary(jnp.hypot, "hypot")
logaddexp = make_binary(jnp.logaddexp, "logaddexp")
heaviside = make_binary(jnp.heaviside, "heaviside")
gcd = make_binary(jnp.gcd, "gcd")
lcm = make_binary(jnp.lcm, "lcm")
inner = make_binary(jnp.inner, "inner")
outer = make_binary(jnp.outer, "outer")
kron = make_binary(jnp.kron, "kron")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference: operators/scale_op.cc"""
    def f(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out.astype(v.dtype)

    out = apply(f, x, unwrap(scale), unwrap(bias), name="scale")
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def clip(x, min=None, max=None, name=None):  # noqa: A002
    return apply(lambda v, lo, hi: jnp.clip(v, lo, hi), x, unwrap(min),
                 unwrap(max), name="clip")


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), x, name="stanh")


def logit(x, eps=None, name=None):
    def f(v):
        u = v if eps is None else jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(u / (1.0 - u))

    return apply(f, x, name="logit")


def multiplex(inputs, index, name=None):
    def f(idx, *ins):
        stacked = jnp.stack(ins, 0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]

    return apply(f, index, *inputs, name="multiplex")


def add_n(inputs, name=None):
    """reference: operators/sum_op.cc"""
    if isinstance(inputs, Tensor):
        return inputs
    return apply(lambda *xs: sum(xs[1:], xs[0]), *inputs, name="add_n")


# -- reductions -------------------------------------------------------------
def _reduce(jnp_fn, opname):
    def op(x, axis=None, keepdim=False, name=None):
        return apply(lambda v: jnp_fn(v, axis=axis_arg(axis), keepdims=keepdim),
                     x, name=opname)

    op.__name__ = opname
    return op


sum = _reduce(jnp.sum, "sum")  # noqa: A001
mean = _reduce(jnp.mean, "mean")
prod = _reduce(jnp.prod, "prod")
max = _reduce(jnp.max, "max")  # noqa: A001
min = _reduce(jnp.min, "min")  # noqa: A001
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")
nansum = _reduce(jnp.nansum, "nansum")
nanmean = _reduce(jnp.nanmean, "nanmean")


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.all(v, axis=axis_arg(axis), keepdims=keepdim),
                 x, differentiable=False, name="all")


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.any(v, axis=axis_arg(axis), keepdims=keepdim),
                 x, differentiable=False, name="any")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jax.scipy.special.logsumexp(
        v, axis=axis_arg(axis), keepdims=keepdim), x, name="logsumexp")


def cumsum(x, axis=None, dtype=None, name=None):
    def f(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=dtype)
        return jnp.cumsum(v, axis=int(axis), dtype=dtype)

    return apply(f, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda v: jnp.cumprod(v, axis=dim, dtype=dtype), x,
                 name="cumprod")


# -- predicates -------------------------------------------------------------
def isfinite(x, name=None):
    return apply(jnp.isfinite, x, differentiable=False, name="isfinite")


def isinf(x, name=None):
    return apply(jnp.isinf, x, differentiable=False, name="isinf")


def isnan(x, name=None):
    return apply(jnp.isnan, x, differentiable=False, name="isnan")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), x, name="nan_to_num")


# -- matmul family (MXU path) ----------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """reference: operators/matmul_v2_op.cc — on TPU this lowers straight to
    an MXU dot_general; bf16 inputs hit the systolic array natively."""
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply(f, x, y, name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return apply(jnp.matmul, x, y, name="bmm")


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y, name="dot")


def mv(x, vec, name=None):
    return apply(jnp.matmul, x, vec, name="mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                 input, x, y, name="addmm")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset, axis1, axis2), x, name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset, axis1, axis2), x,
                 name="diagonal")


def einsum(equation, *operands, name=None):
    ops = operands[0] if len(operands) == 1 and \
        isinstance(operands[0], (list, tuple)) else operands
    return apply(lambda *xs: jnp.einsum(equation, *xs), *ops, name="einsum")


def increment(x, value=1.0, name=None):
    x._value = x._value + value
    return x


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """reference: paddle.diff (finite differences along an axis)."""
    args = [x]
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)

    def f(v, *rest):
        i = 0
        pre = post = None
        if prepend is not None:
            pre = rest[i]
            i += 1
        if append is not None:
            post = rest[i]
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=post)

    return apply(f, *args, name="diff")


def deg2rad(x, name=None):
    return apply(jnp.deg2rad, x, name="deg2rad")


def rad2deg(x, name=None):
    return apply(jnp.rad2deg, x, name="rad2deg")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(
        v, axis=axis_arg(axis), keepdims=keepdim), x, name="count_nonzero")


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize sub-tensors along ``axis`` to at most ``max_norm`` in
    p-norm (reference: paddle.renorm)."""
    def f(v):
        dims = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims,
                        keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return v * factor

    return apply(f, x, name="renorm")


def tanh_(x, name=None):
    """Inplace tanh (reference: paddle.tanh_), differentiable via tape
    rebinding."""
    from ._helper import inplace_apply

    return inplace_apply(jnp.tanh, x, name="tanh_")
