"""Linear-algebra ops (reference: python/paddle/tensor/linalg.py; CUDA path
cusolver/cublas via operators/math/, here jnp.linalg → XLA)."""
from __future__ import annotations

import jax.numpy as jnp

from ._helper import apply, axis_arg, unwrap
from .manipulation import t  # noqa: F401 (re-export)
from .math import bmm, dot, matmul, mm, mv  # noqa: F401 (re-export)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(v):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(v)))
        if axis is None:
            vv = v.reshape(-1)
            return jnp.linalg.norm(vv, ord=p, keepdims=keepdim)
        a = axis_arg(axis)
        if isinstance(a, tuple) and len(a) == 1:
            a = a[0]
        return jnp.linalg.norm(v, ord=None if p == "fro" else p, axis=a,
                               keepdims=keepdim)

    return apply(f, x, name="norm")


def dist(x, y, p=2, name=None):
    def f(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)

    return apply(f, x, y, name="dist")


def cond(x, p=None, name=None):
    return apply(lambda v: jnp.linalg.cond(v, p), x, name="cond")


def cholesky(x, upper=False, name=None):
    def f(v):
        lower = jnp.linalg.cholesky(v)
        return jnp.swapaxes(lower, -1, -2) if upper else lower

    return apply(f, x, name="cholesky")


def inverse(x, name=None):
    return apply(jnp.linalg.inv, x, name="inverse")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.pinv(v, rcond=rcond,
                                           hermitian=hermitian), x, name="pinv")


def det(x, name=None):
    return apply(jnp.linalg.det, x, name="det")


def slogdet(x, name=None):
    def f(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return apply(f, x, name="slogdet")


def svd(x, full_matrices=False, name=None):
    return apply(lambda v: jnp.linalg.svd(v, full_matrices=full_matrices),
                 x, name="svd")


def qr(x, mode="reduced", name=None):
    return apply(lambda v: jnp.linalg.qr(v, mode=mode), x, name="qr")


def eig(x, name=None):
    return apply(jnp.linalg.eig, x, differentiable=False, name="eig")


def eigh(x, UPLO="L", name=None):
    return apply(lambda v: jnp.linalg.eigh(v, UPLO=UPLO), x, name="eigh")


def eigvals(x, name=None):
    return apply(jnp.linalg.eigvals, x, differentiable=False, name="eigvals")


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x,
                 name="eigvalsh")


def matrix_power(x, n, name=None):
    return apply(lambda v: jnp.linalg.matrix_power(v, n), x,
                 name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.matrix_rank(v, tol),
                 x, differentiable=False, name="matrix_rank")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax

    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)

    return apply(f, x, y, name="triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    import jax

    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)

    return apply(f, x, y, name="cholesky_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    return apply(lambda a, b: jnp.linalg.lstsq(a, b, rcond=rcond)[0], x, y,
                 name="lstsq")


def multi_dot(x, name=None):
    return apply(lambda *vs: jnp.linalg.multi_dot(vs), *x, name="multi_dot")


def cross(x, y, axis=None, name=None):
    return apply(lambda a, b: jnp.cross(a, b, axis=axis if axis is not None
                                        else -1), x, y, name="cross")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda v: jnp.cov(v, rowvar=rowvar,
                                   ddof=1 if ddof else 0), x, name="cov")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), x, name="corrcoef")
