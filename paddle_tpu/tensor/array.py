"""Tensor-array ops (reference: python/paddle/tensor/array.py over
LoDTensorArray + operators/array_read_write ops). Functional state makes
the array a plain python list in eager mode; inside ``static.nn``
control flow, use stacked tensors + lax loops instead (SURVEY §7)."""
from __future__ import annotations

from ..framework.tensor import Tensor

__all__ = ["create_array", "array_length", "array_read", "array_write"]


def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list) if initialized_list else []
    for v in arr:
        if not isinstance(v, Tensor):
            raise TypeError("initialized_list must contain Tensors")
    return arr


def array_length(array):
    return len(array)


def array_read(array, i):
    return array[int(i)]


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = int(i)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(f"array_write index {i} beyond length "
                         f"{len(array)}")
    return array
