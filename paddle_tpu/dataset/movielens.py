"""reference: python/paddle/dataset/movielens.py (rating reader)."""
from ..text.datasets import Movielens
from ._adapt import reader_from

_make = reader_from(Movielens)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
