"""Shared adapter: Dataset class -> legacy reader factory."""
from __future__ import annotations


def reader_from(dataset_factory):
    def make(*args, **kwargs):
        def reader():
            ds = dataset_factory(*args, **kwargs)
            for i in range(len(ds)):
                item = ds[i]
                yield tuple(item) if isinstance(item, (tuple, list)) \
                    else (item,)

        return reader

    return make
