"""reference: python/paddle/dataset/mnist.py (train/test readers)."""
from ..vision.datasets import MNIST
from ._adapt import reader_from

_make = reader_from(MNIST)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
