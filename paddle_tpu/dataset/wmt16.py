"""reference: python/paddle/dataset/wmt16.py (translation pairs)."""
from ..text.datasets import WMT16
from ._adapt import reader_from

_make = reader_from(WMT16)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
