"""reference: python/paddle/dataset/voc2012.py (segmentation reader)."""
from ..vision.datasets import VOC2012
from ._adapt import reader_from

_make = reader_from(VOC2012)


def train(**kw):
    return _make(mode="train", **kw)


def valid(**kw):
    return _make(mode="valid", **kw)


def test(**kw):
    return _make(mode="test", **kw)
