"""reference: python/paddle/dataset/imikolov.py."""
from ..text.datasets import Imikolov
from ._adapt import reader_from

_make = reader_from(Imikolov)


def train(word_idx=None, n=5, **kw):
    return _make(mode="train", window_size=n, **kw)


def test(word_idx=None, n=5, **kw):
    return _make(mode="test", window_size=n, **kw)
