"""reference: python/paddle/dataset/wmt14.py (translation pairs)."""
from ..text.datasets import WMT14
from ._adapt import reader_from

_make = reader_from(WMT14)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
