"""reference: python/paddle/dataset/flowers.py (102-flowers reader)."""
from ..vision.datasets import Flowers
from ._adapt import reader_from

_make = reader_from(Flowers)


def train(**kw):
    return _make(mode="train", **kw)


def valid(**kw):
    return _make(mode="valid", **kw)


def test(**kw):
    return _make(mode="test", **kw)
