"""Legacy reader-style dataset namespace (reference:
python/paddle/dataset/ — mnist.py, cifar.py, imdb.py, uci_housing.py…
each exposing train()/test() generator factories consumed by
paddle.batch / paddle.reader decorators).

Thin adapters over the modern Dataset classes (vision/datasets,
text/datasets): same reader-function contract, one sample tuple per
yield.
"""
from __future__ import annotations

from . import cifar, imdb, imikolov, mnist, uci_housing  # noqa: F401

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing"]
