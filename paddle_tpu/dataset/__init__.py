"""Legacy reader-style dataset namespace (reference:
python/paddle/dataset/ — mnist.py, cifar.py, imdb.py, uci_housing.py…
each exposing train()/test() generator factories consumed by
paddle.batch / paddle.reader decorators).

Thin adapters over the modern Dataset classes (vision/datasets,
text/datasets): same reader-function contract, one sample tuple per
yield.
"""
from __future__ import annotations

from . import (cifar, conll05, flowers, imdb, imikolov, mnist,  # noqa: F401
               movielens, uci_housing, voc2012, wmt14, wmt16)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing", "conll05",
           "flowers", "movielens", "voc2012", "wmt14", "wmt16"]
