"""reference: python/paddle/dataset/imdb.py."""
from ..text.datasets import Imdb
from ._adapt import reader_from

_make = reader_from(Imdb)


def train(word_idx=None, **kw):
    return _make(mode="train", **kw)


def test(word_idx=None, **kw):
    return _make(mode="test", **kw)
