"""reference: python/paddle/dataset/conll05.py (SRL corpus reader)."""
from ..text.datasets import Conll05st
from ._adapt import reader_from

_make = reader_from(Conll05st)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
