"""reference: python/paddle/dataset/cifar.py (cifar10/100 readers)."""
from ..vision.datasets import Cifar10, Cifar100
from ._adapt import reader_from

_make10 = reader_from(Cifar10)
_make100 = reader_from(Cifar100)


def train10(**kw):
    return _make10(mode="train", **kw)


def test10(**kw):
    return _make10(mode="test", **kw)


def train100(**kw):
    return _make100(mode="train", **kw)


def test100(**kw):
    return _make100(mode="test", **kw)
