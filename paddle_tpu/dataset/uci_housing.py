"""reference: python/paddle/dataset/uci_housing.py."""
from ..text.datasets import UCIHousing
from ._adapt import reader_from

_make = reader_from(UCIHousing)


def train(**kw):
    return _make(mode="train", **kw)


def test(**kw):
    return _make(mode="test", **kw)
