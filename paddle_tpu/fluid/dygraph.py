"""fluid.dygraph compat (reference: fluid/dygraph/{base,layers,jit}.py).

The tape IS always on in this framework (eager by default, like
paddle 2.x), so ``guard()`` is a no-op context and ``enabled()`` is
True; ``to_variable`` is ``to_tensor``.
"""
import contextlib

from ..framework import to_tensor as to_variable  # noqa: F401
from ..jit import TracedLayer  # noqa: F401
from ..nn import Layer  # noqa: F401
from ..nn import Embedding, Linear  # noqa: F401
from ..nn.layer.container import LayerList, Sequential  # noqa: F401
from ..autograd import no_grad  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """Dygraph mode is the default; the guard is a compat no-op."""
    yield


def enabled():
    return True


def to_static(*a, **kw):
    from ..jit import to_static as _ts

    return _ts(*a, **kw)
