"""fluid.layers compat: the 1.x flat op namespace (reference:
fluid/layers/{nn,tensor,control_flow,loss}.py — thousands of lines of
LayerHelper plumbing whose TPU translation is simply the modern
functional/tensor ops under their legacy names).
"""
import paddle_tpu as _p
import paddle_tpu.nn.functional as _F
from ..nn.functional import *  # noqa: F401,F403
from ..tensor import *  # noqa: F401,F403
from ..static.nn import case, cond, switch_case, while_loop  # noqa: F401
from ..tensor.creation import (arange, assign, full, linspace,  # noqa: F401
                               ones, ones_like, zeros, zeros_like)
from ..tensor import concat, reshape, shape, slice, split, squeeze  # noqa: F401
from ..vision.detection import (  # noqa: F401
    anchor_generator, bipartite_match, box_clip, box_coder,
    collect_fpn_proposals, density_prior_box, distribute_fpn_proposals,
    generate_proposal_labels, generate_proposals, iou_similarity,
    locality_aware_nms, matrix_nms, mine_hard_examples, multiclass_nms,
    polygon_box_transform, retinanet_detection_output, rpn_target_assign,
    target_assign)

# 1.x names whose modern spelling differs


def fill_constant(shape, dtype, value, force_cpu=False, out=None,
                  name=None):
    """1.x argument order (shape, dtype, value) vs modern full(shape,
    value, dtype) (reference fluid/layers/tensor.py fill_constant)."""
    return full(shape, value, dtype=dtype)
reduce_sum = _p.sum
reduce_mean = _p.mean
reduce_max = _p.max
reduce_min = _p.min
elementwise_add = _p.add
elementwise_sub = _p.subtract
elementwise_mul = _p.multiply
elementwise_div = _p.divide
hard_sigmoid = _F.hardsigmoid
hard_swish = _F.hardswish
soft_relu = _F.softplus


def create_tensor(dtype, name=None, persistable=False):
    """1.x signature create_tensor(dtype, ...) — an uninitialized scalar
    variable of ``dtype`` (reference fluid/layers/tensor.py create_tensor),
    not zeros(shape)."""
    t = _p.zeros([], dtype=dtype)
    t.name = name or ""
    t.persistable = persistable
    return t


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,  # noqa: A002
       act=None, name=None):
    """The 1.x fully-connected layer-op (reference fluid/layers/nn.py
    fc): creates (or reuses under a ParamAttr name) a weight, matmuls,
    adds bias, applies act. Eager translation: a fresh Linear module's
    forward — for persistent weights use nn.Linear directly."""
    import numpy as np

    from .. import nn as _nn

    feat = 1
    for d in input.shape[num_flatten_dims:]:
        feat *= int(d)
    lin = _nn.Linear(feat, size, weight_attr=param_attr,
                     bias_attr=bias_attr)
    x = input.reshape(list(input.shape[:num_flatten_dims]) + [feat])
    out = lin(x)
    if act:
        out = getattr(_F, act)(out)
    return out


def data(name, shape, dtype="float32", lod_level=0):
    raise NotImplementedError(
        "fluid.layers.data builds static graph feeds; trace with "
        "paddle.jit.to_static + InputSpec instead (SURVEY §7)")
