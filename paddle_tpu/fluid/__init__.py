"""paddle.fluid compat namespace (reference: python/paddle/fluid/).

The reference's 2.x API keeps the 1.x ``fluid`` package importable and
most user code of the era reaches through it. Here it is a thin façade
over the real modules: the dygraph engine is the tape (autograd/), the
layer library is nn/, static programs are Plans (static/). Only names
with a meaningful TPU translation are carried; the deleted-by-design
machinery (Executor scopes, ParallelExecutor, transpilers) raises with
pointers to the replacement (SURVEY §7).
"""
from .. import nn as _nn  # noqa: F401
from ..core.flags import get_flags, set_flags  # noqa: F401
from ..core.place import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa: F401
                          TPUPlace, XPUPlace, device_count, is_compiled_with_tpu)
from ..framework.param_attr import ParamAttr  # noqa: F401
from ..framework.tensor import Parameter, Tensor  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..static import (InputSpec, Program, default_main_program,  # noqa: F401
                      default_startup_program)
from .. import io  # noqa: F401
from .. import metric as metrics  # noqa: F401
from .. import optimizer  # noqa: F401
from .. import regularizer  # noqa: F401
from ..autograd import grad as gradients  # noqa: F401
from . import dygraph, layers, nets  # noqa: F401
from ..io import DataLoader  # noqa: F401

is_compiled_with_cuda = is_compiled_with_tpu  # CUDA-era probe → TPU


class Executor:
    """The reference Executor runs ProgramDescs over Scopes
    (fluid/executor.py). Functional XLA execution has no Scope; static
    programs are ``paddle.static.Plan`` artifacts run via ``plan.run``/
    ``jit.load``. Kept only to give 1.x scripts a clear error."""

    def __init__(self, place=None):
        raise NotImplementedError(
            "fluid.Executor is superseded: trace the model with "
            "paddle.jit.to_static / save, run via paddle.static.Plan or "
            "paddle.inference.create_predictor (SURVEY §7 row N17)")
