"""fluid.nets compat: the 1.x composite blocks (reference:
fluid/nets.py — simple_img_conv_pool, glu, scaled_dot_product_attention
composed from layer ops).
"""
import paddle_tpu.nn.functional as _F
from ..nn.functional import glu, scaled_dot_product_attention  # noqa: F401


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,  # noqa: A002
                         pool_stride, pool_padding=0, pool_type="max",
                         conv_stride=1, conv_padding=0, conv_dilation=1,
                         conv_groups=1, param_attr=None, bias_attr=None,
                         act=None, use_cudnn=True):
    """conv2d → act → pool (reference nets.py:31, full parameter set).
    Eager translation with a fresh conv; use nn.Conv2D for persistent
    weights. ``use_cudnn`` is accepted for parity (XLA picks kernels)."""
    from .. import nn as _nn

    conv = _nn.Conv2D(int(input.shape[1]), num_filters, filter_size,
                      stride=conv_stride, padding=conv_padding,
                      dilation=conv_dilation, groups=conv_groups,
                      weight_attr=param_attr, bias_attr=bias_attr)
    out = conv(input)
    if act:
        out = getattr(_F, act)(out)
    pool = _F.max_pool2d if pool_type == "max" else _F.avg_pool2d
    return pool(out, kernel_size=pool_size, stride=pool_stride,
                padding=pool_padding)
