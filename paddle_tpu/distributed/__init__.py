"""paddle.distributed equivalent — TPU-native distributed runtime.

Reference surface: python/paddle/distributed/ (collective.py, parallel.py,
spawn.py, fleet/). TPU design: SURVEY.md §5/§7 — mesh axes replace rings,
GSPMD/pjit replaces program surgery, jax.distributed replaces TCP
bootstrap.
"""
from . import fleet  # noqa: F401
from .collective import (ReduceOp, all_gather, all_reduce, alltoall,  # noqa: F401
                         barrier, broadcast, get_group, recv, reduce,
                         reduce_scatter, scatter, send, split)
from .env import (ParallelEnv, get_rank, get_world_size,  # noqa: F401
                  init_parallel_env, is_initialized)
from .mesh import (P, axis_size, create_mesh, get_mesh, init_mesh,  # noqa: F401
                   set_mesh, sharding)
from .parallel import DataParallel  # noqa: F401
from . import primitives  # noqa: F401
from .parallel_layers import (ColumnParallelLinear, ParallelEmbedding,  # noqa: F401
                              RowParallelLinear, VocabParallelEmbedding)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py. Multi-host TPU jobs are launched by
    the cluster scheduler (one process per host); in-process spawn of extra
    jax runtimes is not supported — use paddle_tpu.distributed.launch."""
    raise NotImplementedError(
        "spawn: launch one process per host via `python -m "
        "paddle_tpu.distributed.launch` (env protocol PADDLE_TRAINER_*).")
