"""Dygraph data parallel (reference: python/paddle/fluid/dygraph/parallel.py
DataParallel:322 + imperative Reducer reducer.cc).

On TPU, eager multi-process DP syncs grads at step time (see
fleet_base.DistributedOptimizer.step). The Reducer's overlap-with-backward
machinery is unnecessary (XLA fuses reductions in the compiled path), but
its BUCKETING survives in spirit: apply_collective_grads flattens every
gradient into one fused buffer and performs a SINGLE allreduce — one host
round-trip per step instead of one per parameter (the eager collective
backend is host-staged, collective.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from .collective import all_reduce, broadcast
from .env import get_world_size, init_parallel_env  # noqa: F401


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        if get_world_size() > 1:
            for p in layers.parameters():
                broadcast(p, src=0)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        if get_world_size() <= 1:
            return
        n = get_world_size()
        with_grad = [p for p in self._layers.parameters()
                     if p.grad is not None]
        if not with_grad:
            return
        # fused-bucket allreduce (reference Reducer::MarkGroupReady
        # concat-and-allreduce, reducer.cc:463-559): ONE collective for
        # the whole model
        flats = [jnp.ravel(p.grad._value).astype(jnp.float32)
                 for p in with_grad]
        sizes = [int(f.size) for f in flats]
        bucket = Tensor(jnp.concatenate(flats))
        all_reduce(bucket)
        merged = bucket._value / n
        offset = 0
        for p, size in zip(with_grad, sizes):
            piece = merged[offset:offset + size]
            p.grad._value = piece.reshape(p.grad._value.shape).astype(
                p.grad._value.dtype)
            offset += size

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
