"""Dygraph data parallel (reference: python/paddle/fluid/dygraph/parallel.py
DataParallel:322 + imperative Reducer reducer.cc).

On TPU, eager multi-process DP syncs grads at step time (see
fleet_base.DistributedOptimizer.step); the Reducer's bucketing/overlap
machinery is unnecessary — XLA fuses gradient reductions in the compiled
path, and eager sync is one fused host call. DataParallel therefore only
needs to (a) broadcast initial params, (b) mark the model so optimizers
know to sync.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .collective import all_reduce, broadcast
from .env import get_world_size, init_parallel_env  # noqa: F401


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        if get_world_size() > 1:
            for p in layers.parameters():
                broadcast(p, src=0)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        if get_world_size() <= 1:
            return
        n = get_world_size()
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad)
                p.grad._value = p.grad._value / n

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
