"""SPMD collective primitives for use inside pjit/shard_map.

The compiled-regime data plane (reference's NCCL calls inside CUDA graphs —
c_allreduce_op.h:157, send_v2/recv_v2 — become these XLA collectives over
ICI; SURVEY.md §5 translation table).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute
all_gather = lax.all_gather
all_to_all = lax.all_to_all
axis_index = lax.axis_index


def psum_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


reduce_scatter = psum_scatter


def ring_permute(x, axis_name, shift=1):
    """Cyclic shift along a mesh axis (pipeline/ring-attention building
    block; replaces the reference's send_v2/recv_v2 p2p ops)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
