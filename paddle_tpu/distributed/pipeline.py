"""Pipeline parallelism over a mesh axis.

TPU-native replacement for the reference pipeline stack
(reference: fleet meta_optimizers/pipeline_optimizer.py:136 splitting the
program by op_device + send_v2/recv_v2 ops; PipelineTrainer/SectionWorker
section_worker.cc:34 F-then-B thread-per-stage schedule).

Here the whole pipeline is ONE compiled SPMD computation:
  - transformer blocks' params are stacked into [pp, layers_per_stage, ...]
    (or [pp, v, layers_per_virtual, ...] when interleaved) with the stage
    axis sharded over mesh axis 'pp' (shard_map manual);
  - microbatches stream through stages with lax.ppermute — the XLA
    collective-permute that replaces the reference's per-microbatch
    ncclSend/ncclRecv (send_v2_op.cu.cc);
  - the schedule loop is a lax.scan, so forward AND backward of the whole
    schedule differentiate through the permute chain — no per-stage
    hand-written backward passes (section_worker.cc:77-93);
  - other mesh axes (dp/tp/sp) stay in GSPMD 'auto' mode inside the stage
    body, composing pipeline with tensor/data parallelism.

Schedules:
  - v_virtual=1: GPipe fill-drain — n_micro + pp - 1 ticks of a full
    stage's layers each; bubble fraction (pp-1)/(n_micro+pp-1).
  - v_virtual=v>1: interleaved/circular (each device owns v non-contiguous
    "virtual stages"; microbatches circle the ring v times) —
    v·n_micro + pp - 1 ticks of 1/v the work each; bubble fraction
    (pp-1)/(v·n_micro+pp-1), i.e. v× smaller than GPipe AND than the
    reference's F-then-B. Requires n_micro >= pp.

Loss egress: when ``head_fn`` is given, the loss head runs INSIDE the
manual region — every stage computes it in SPMD lockstep (no wall-clock
cost vs one stage computing while the rest idle), the last stage's value
is selected, and only the SCALAR is psum'd across 'pp'. Without head_fn
the full activation buffer is shared via masked psum (needed by the
manual-sp composition, where the head must see the sp-sharded output).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..profiler.trace import annotate as _annotate
from ._compat import shard_map as _shard_map


def stack_block_params(block_param_lists):
    """[{name: val} per layer] → {name: [L, ...] stacked}."""
    names = list(block_param_lists[0].keys())
    return {n: jnp.stack([bp[n] for bp in block_param_lists], 0)
            for n in names}


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stacked_params: Any,
                   x, n_micro: int, pp_axis: str = "pp",
                   sp_axis: str = None, v_virtual: int = 1,
                   head_fn: Optional[Callable] = None,
                   head_args: tuple = (), stage_aux: bool = False):
    """Run x [batch, ...] through the pipelined stacked blocks.

    stage_fn(params_one_chunk, x_mb) -> y_mb applies one (virtual) stage's
    layers to one microbatch. stacked_params leaves are [pp, ...] for
    v_virtual=1 or [pp, v, ...] for interleaved; x is split into n_micro
    microbatches along dim 0.

    head_fn(full_output) -> scalar: optional loss head computed inside the
    region (see module docstring); returns the scalar instead of the
    activations.

    stage_aux: when True, stage_fn returns ``(y_mb, aux_scalar)`` — a
    per-microbatch auxiliary scalar (e.g. the MoE load-balance loss of the
    stage's blocks). Aux values are accumulated over the ticks where the
    stage holds REAL data (fill/drain garbage ticks masked out), psum'd
    over 'pp' so every stage's layers contribute, and averaged over
    microbatches. pipeline_apply then returns ``(out, aux)``.

    sp_axis: when set (sequence parallelism composed with pipeline), the
    shard_map is manual over BOTH axes — x's seq dim (dim 1) stays sharded
    over sp_axis and stage_fn sees the local sequence shard (its attention
    must then run the in-context ring, see models/gpt.py). Nested
    shard_maps over the same axis are rejected by the partitioner, so
    manual-over-both is the composition mechanism.
    """
    pp = mesh.shape.get(pp_axis, 1)
    v = v_virtual
    if sp_axis is not None and mesh.shape.get(sp_axis, 1) <= 1:
        sp_axis = None
    if sp_axis is not None and head_fn is not None:
        raise ValueError("head_fn is not supported under manual sp "
                         "(the head must see the sp-sharded output)")
    if v > 1 and n_micro < pp:
        raise ValueError(
            f"interleaved schedule needs n_micro >= pp ({n_micro} < {pp})")
    if pp == 1:
        sliced = jax.tree_util.tree_map(
            lambda a: a[0] if v == 1 else a[0].reshape(
                (-1,) + tuple(a.shape[3:])), stacked_params)
        mbs = _to_microbatches(x, n_micro)

        def one_mb(mb):
            with _annotate("pp/stage"):
                return stage_fn(sliced, mb)

        out = jax.lax.map(one_mb, mbs)
        if stage_aux:
            out, auxs = out
            aux = jnp.sum(auxs.astype(jnp.float32)) / n_micro
        full = _from_microbatches(out, x.shape)
        res = head_fn(full, *head_args) if head_fn is not None else full
        return (res, aux) if stage_aux else res

    compute_dtype = x.dtype
    # XLA:CPU's AllReducePromotion pass crashes on bf16 all-reduce; the
    # shard_map TRANSPOSE of a replicated input inserts exactly that (psum
    # of input cotangents over pp). Promote the boundary dtype on CPU only;
    # TPU keeps native bf16 transfers.
    from ..core.place import target_platform
    boundary_f32 = (target_platform() == "cpu"
                    and compute_dtype == jnp.bfloat16)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(pp_axis), stacked_params)
    # jax < 0.5 (no ``jax.shard_map``): the old experimental dialect
    # cannot TRANSPOSE a partially-manual region (``auto=`` non-empty —
    # the same limitation ring_attention works around), so the schedule
    # goes manual over EVERY mesh axis there instead. The specs are
    # unchanged: params stay split over 'pp' only, so the entry reshards
    # replicate them over dp/tp and each of those ranks runs the stage
    # redundantly — same math, gradient-exact (the transpose psums the
    # replicated params' cotangents over the extra axes, measured exact
    # against the modern partial-manual program), only the partitioning
    # dialect differs. dp/tp parallelism inside the schedule is a
    # modern-jax (GSPMD-auto) feature; on old jax it degrades to
    # replication, never to wrong numbers.
    legacy_all_manual = not hasattr(jax, "shard_map")
    manual = None if legacy_all_manual else \
        frozenset({pp_axis} if sp_axis is None else {pp_axis, sp_axis})
    # params are pp-sharded but REPLICATED over sp (and over EVERY other
    # axis in the legacy all-manual fallback): the shard_map transpose
    # psums their cotangents over the replicated axes — promote that
    # boundary too on CPU (same XLA:CPU bf16-collective crash as above;
    # TPU unaffected).
    param_f32 = boundary_f32 and (sp_axis is not None or legacy_all_manual)

    def _pf(a):
        return a.astype(jnp.float32) if (param_f32
                                         and a.dtype == jnp.bfloat16) else a
    # xs is [n_micro, mb, seq, ...]: seq (dim 2) sharded over sp when set
    x_spec = P() if sp_axis is None else P(None, None, sp_axis)
    out_spec = P() if head_fn is not None else x_spec
    if stage_aux:
        out_spec = (out_spec, P())
    # head params/batch enter as explicit inputs (replicated over the
    # manual axes; their dp/tp shardings ride the auto axes) — closures
    # over outer-traced sharded values are rejected inside shard_map
    head_specs = jax.tree_util.tree_map(lambda _: P(), head_args)

    @partial(_shard_map, mesh=mesh,
             in_specs=(param_specs, x_spec, head_specs), out_specs=out_spec,
             check_vma=False, axis_names=manual)
    def pipelined(params, xs, head_args):
        # params leaves: [1, ...] local slice; xs: [n_micro, mb, ...]
        local = jax.tree_util.tree_map(
            lambda a: a[0].astype(compute_dtype)
            if (param_f32 and a.dtype == jnp.float32
                and compute_dtype == jnp.bfloat16) else a[0], params)
        stage = jax.lax.axis_index(pp_axis)
        n_ticks = v * n_micro + pp - 1
        mb_shape = xs.shape[1:]
        # carry dtype: f32 on CPU+bf16 so the inter-stage ppermute (a
        # collective inside the manual region) never runs in bf16
        carry_dtype = jnp.float32 if boundary_f32 else compute_dtype
        state0 = jnp.zeros(mb_shape, carry_dtype)
        outputs0 = jnp.zeros(xs.shape, carry_dtype)
        # circuit-return buffer (interleaved: finished circuits wait here
        # until stage 0 re-injects them); unused for v == 1
        ret0 = jnp.zeros(xs.shape, carry_dtype)

        def tick(carry, t):
            prev_out, ret, outputs, aux_acc = carry
            # stage i receives stage i-1's last output (ring; stage 0's
            # recv feeds the circuit-return buffer)
            # pp/* named scopes: schedule-phase names baked into the
            # compiled program so device traces attribute time to the
            # inter-stage permute vs the stage compute (profiler/trace.py)
            with _annotate("pp/ppermute"):
                recv = jax.lax.ppermute(
                    prev_out, pp_axis,
                    [(i, (i + 1) % pp) for i in range(pp)])
            if v > 1:
                # a completed circuit item arrives back at stage 0 at tick
                # t with microbatch id (t - pp) mod n_micro
                ret_idx = jnp.clip((t - pp) % n_micro, 0, n_micro - 1)
                cur_ret = jax.lax.dynamic_index_in_dim(
                    ret, ret_idx, 0, keepdims=False)
                ret = jax.lax.dynamic_update_index_in_dim(
                    ret, jnp.where((stage == 0) & (t >= pp), recv, cur_ret),
                    ret_idx, 0)
            # stage 0 at tick t processes (circuit c, microbatch m)
            mb_idx = jnp.clip(t % n_micro, 0, n_micro - 1) if v > 1 else \
                jnp.clip(t, 0, n_micro - 1)
            circuit0 = t // n_micro if v > 1 else jnp.zeros_like(t)
            fresh = jax.lax.dynamic_index_in_dim(
                xs, mb_idx, 0, keepdims=False).astype(carry_dtype)
            if v > 1:
                returned = jax.lax.dynamic_index_in_dim(
                    ret, mb_idx, 0, keepdims=False)
                stage0_in = jnp.where(circuit0 == 0, fresh, returned)
            else:
                stage0_in = fresh
            inp = jnp.where(stage == 0, stage0_in, recv)
            # params for this tick: the circuit this stage is working on
            if v > 1:
                c_s = jnp.clip((t - stage) // n_micro, 0, v - 1)
                chunk = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, c_s, 0, keepdims=False), local)
            else:
                chunk = local
            with _annotate("pp/stage"):
                out = stage_fn(chunk, inp.astype(compute_dtype))
            if stage_aux:
                out, aux = out
                # fill/drain ticks run on garbage zeros — mask their aux.
                # stage s holds real data from tick s to s + v*n_micro - 1.
                busy = (t >= stage) & (t < stage + v * n_micro)
                aux_acc = aux_acc + jnp.where(
                    busy, aux.astype(jnp.float32), 0.0)
            out = out.astype(carry_dtype)
            # the last stage finishing the LAST circuit produces output
            done_t = t - (pp - 1) - (v - 1) * n_micro
            out_idx = jnp.clip(done_t % n_micro if v > 1 else done_t,
                               0, n_micro - 1)
            valid = done_t >= 0
            cur = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, out, cur), out_idx, 0)
            return (out, ret, outputs, aux_acc), None

        (last, _, outputs, aux_acc), _ = jax.lax.scan(
            tick, (state0, ret0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks))
        # every stage's layers contribute their own aux; per-microbatch mean
        aux_total = jax.lax.psum(aux_acc, pp_axis) / n_micro \
            if stage_aux else None
        if sp_axis is not None and stage_aux:
            # local routing groups per sp shard: average their aux
            aux_total = jax.lax.pmean(aux_total, sp_axis)
        if head_fn is not None:
            # loss head on every stage in lockstep; only the last stage's
            # value is real — egress is ONE scalar, not the activations
            full = outputs.reshape((outputs.shape[0] * outputs.shape[1],)
                                   + tuple(outputs.shape[2:]))
            with _annotate("pp/head"):
                loss = head_fn(full.astype(compute_dtype), *head_args)
            loss = jnp.where(stage == pp - 1, loss, 0.0)
            loss = jax.lax.psum(loss.astype(jnp.float32), pp_axis)
            return (loss, aux_total) if stage_aux else loss
        # only the last stage's buffer is the real output; share it
        mask = (stage == pp - 1).astype(outputs.dtype)
        masked = outputs * mask
        if boundary_f32:
            masked = masked.astype(jnp.float32)
        shared = jax.lax.psum(masked, pp_axis)
        return (shared, aux_total) if stage_aux else shared

    mbs = _to_microbatches(x, n_micro)
    if boundary_f32:
        mbs = mbs.astype(jnp.float32)
    if param_f32:
        stacked_params = jax.tree_util.tree_map(_pf, stacked_params)
    out = pipelined(stacked_params, mbs, head_args)
    aux = None
    if stage_aux:
        out, aux = out
    if head_fn is None:
        out = _from_microbatches(out, x.shape).astype(compute_dtype)
    return (out, aux) if stage_aux else out


def _to_microbatches(x, n_micro):
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible into {n_micro} micro"
    return x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))


def _from_microbatches(mbs, orig_shape):
    return mbs.reshape(orig_shape)
