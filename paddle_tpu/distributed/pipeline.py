"""Pipeline parallelism over a mesh axis.

TPU-native replacement for the reference pipeline stack
(reference: fleet meta_optimizers/pipeline_optimizer.py:136 splitting the
program by op_device + send_v2/recv_v2 ops; PipelineTrainer/SectionWorker
section_worker.cc:34 F-then-B thread-per-stage schedule).

Here the whole pipeline is ONE compiled SPMD computation:
  - transformer blocks' params are stacked into [pp, layers_per_stage, ...]
    with the stage axis sharded over mesh axis 'pp' (shard_map manual);
  - microbatches stream through stages with lax.ppermute — the XLA
    collective-permute that replaces the reference's per-microbatch
    ncclSend/ncclRecv (send_v2_op.cu.cc);
  - the fill/drain loop is a lax.scan, so forward AND backward of the whole
    schedule differentiate through the permute chain — no per-stage
    hand-written backward passes (section_worker.cc:77-93);
  - other mesh axes (dp/tp/sp) stay in GSPMD 'auto' mode inside the stage
    body, composing pipeline with tensor/data parallelism.

Bubble note: this is the GPipe fill-drain schedule (n_micro + pp - 1
ticks). The reference syncs every microbatch with cudaDeviceSynchronize
(section_worker.cc:73); here XLA overlaps the permute with compute, and
raising n_micro amortizes the bubble exactly as in GPipe.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_block_params(block_param_lists):
    """[{name: val} per layer] → {name: [L, ...] stacked}."""
    names = list(block_param_lists[0].keys())
    return {n: jnp.stack([bp[n] for bp in block_param_lists], 0)
            for n in names}


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stacked_params: Any,
                   x, n_micro: int, pp_axis: str = "pp",
                   sp_axis: str = None):
    """Run x [batch, ...] through pp×layers_per_stage stacked blocks.

    stage_fn(params_one_stage, x_mb) -> y_mb applies one stage's layers to
    one microbatch. stacked_params leaves are [pp, ...]; x is split into
    n_micro microbatches along dim 0.

    sp_axis: when set (sequence parallelism composed with pipeline), the
    shard_map is manual over BOTH axes — x's seq dim (dim 1) stays sharded
    over sp_axis and stage_fn sees the local sequence shard (its attention
    must then run the in-context ring, see models/gpt.py). Nested
    shard_maps over the same axis are rejected by the partitioner, so
    manual-over-both is the composition mechanism.
    """
    pp = mesh.shape[pp_axis]
    if sp_axis is not None and mesh.shape.get(sp_axis, 1) <= 1:
        sp_axis = None
    if pp == 1:
        sliced = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        mbs = _to_microbatches(x, n_micro)
        out = jax.lax.map(lambda mb: stage_fn(sliced, mb), mbs)
        return _from_microbatches(out, x.shape)

    compute_dtype = x.dtype
    # XLA:CPU's AllReducePromotion pass crashes on bf16 all-reduce; the
    # shard_map TRANSPOSE of a replicated input inserts exactly that (psum
    # of input cotangents over pp). Promote the boundary dtype on CPU only;
    # TPU keeps native bf16 transfers.
    boundary_f32 = (jax.default_backend() == "cpu"
                    and compute_dtype == jnp.bfloat16)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(pp_axis), stacked_params)
    manual = frozenset({pp_axis} if sp_axis is None else {pp_axis, sp_axis})
    # params are pp-sharded but REPLICATED over sp: the shard_map transpose
    # psums their cotangents over sp — promote that boundary too on CPU
    # (same XLA:CPU bf16-collective crash as above; TPU unaffected).
    param_f32 = boundary_f32 and sp_axis is not None

    def _pf(a):
        return a.astype(jnp.float32) if (param_f32
                                         and a.dtype == jnp.bfloat16) else a
    # xs is [n_micro, mb, seq, ...]: seq (dim 2) sharded over sp when set
    x_spec = P() if sp_axis is None else P(None, None, sp_axis)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(param_specs, x_spec), out_specs=x_spec,
             check_vma=False, axis_names=manual)
    def pipelined(params, xs):
        # params leaves: [1, ...] local slice; xs: [n_micro, mb, ...]
        local = jax.tree_util.tree_map(
            lambda a: a[0].astype(compute_dtype)
            if (param_f32 and a.dtype == jnp.float32
                and compute_dtype == jnp.bfloat16) else a[0], params)
        stage = jax.lax.axis_index(pp_axis)
        n_ticks = n_micro + pp - 1
        mb_shape = xs.shape[1:]
        # carry dtype: f32 on CPU+bf16 so the inter-stage ppermute (a
        # collective inside the manual region) never runs in bf16
        carry_dtype = jnp.float32 if boundary_f32 else compute_dtype
        state0 = jnp.zeros(mb_shape, carry_dtype)
        outputs0 = jnp.zeros(xs.shape, carry_dtype)

        def tick(carry, t):
            prev_out, outputs = carry
            # stage i receives stage i-1's last output (ring; stage 0's
            # recv is garbage and masked below)
            recv = jax.lax.ppermute(
                prev_out, pp_axis,
                [(i, (i + 1) % pp) for i in range(pp)])
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0,
                            jax.lax.dynamic_index_in_dim(
                                xs, mb_idx, 0,
                                keepdims=False).astype(carry_dtype),
                            recv)
            out = stage_fn(local, inp.astype(compute_dtype)) \
                .astype(carry_dtype)
            out_idx = jnp.clip(t - (pp - 1), 0, n_micro - 1)
            valid = (t >= pp - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, out, cur), out_idx, 0)
            return (out, outputs), None

        (last, outputs), _ = jax.lax.scan(tick, (state0, outputs0),
                                          jnp.arange(n_ticks))
        # only the last stage's buffer is the real output; share it
        mask = (stage == pp - 1).astype(outputs.dtype)
        masked = outputs * mask
        if boundary_f32:
            return jax.lax.psum(masked.astype(jnp.float32), pp_axis)
        return jax.lax.psum(masked, pp_axis)

    mbs = _to_microbatches(x, n_micro)
    if boundary_f32:
        mbs = mbs.astype(jnp.float32)
    if param_f32:
        stacked_params = jax.tree_util.tree_map(_pf, stacked_params)
    out = pipelined(stacked_params, mbs)
    return _from_microbatches(out, x.shape).astype(compute_dtype)


def _to_microbatches(x, n_micro):
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible into {n_micro} micro"
    return x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))


def _from_microbatches(mbs, orig_shape):
    return mbs.reshape(orig_shape)
