"""Quantized collectives: EQuARX-style compressed AllReduce for the
DP gradient path (ROADMAP item 3b; "EQuARX: Efficient Quantized
AllReduce in XLA", PAPERS.md).

A data-parallel step moves every gradient byte across the mesh once
per step, and on multi-host meshes that AllReduce IS the comm phase
the profiler accounts (``comm/collective_bytes_per_step``). EQuARX's
observation: the reduction tolerates low-precision *transport* as long
as *accumulation* stays high-precision — so quantize each hop of the
ring, not the math:

1. **Blockwise int8 quantization.** The flat gradient is cut into
   ``block``-element blocks; each block ships as int8 with one f32
   scale (``amax / 127``). Per-block scaling is what makes one outlier
   cost one block's precision instead of the whole tensor's (the same
   reasoning as the per-page per-head KV scales in
   ``serving/paged_cache.py`` and the per-tensor amax idiom of
   ``ops/int8_matmul.py``).
2. **Reduce-scatter in low precision, accumulate in f32.** A classic
   ring reduce-scatter (``N - 1`` ``ppermute`` hops) where every hop's
   payload is the quantized partial sum + its block scales; the
   receiver dequantizes, adds its own f32 shard, and re-quantizes for
   the next hop. Wire bytes per hop: ``T/N`` int8 + ``T/(N·block)``
   f32 scales, vs ``4·T/N`` for the f32 ring.
3. **Quantized all-gather.** Each device quantizes its fully-reduced
   shard once and all-gathers int8 + scales; everyone dequantizes
   locally.

Counted result-buffer bytes (what ``profiler.collective_stats``
measures): ``(N-1)/N·T + T`` int8 + scale overhead ≈ ``2T`` bytes vs
the f32 AllReduce's ``4T`` — ≤ 0.5x before scale overhead, ≤ 0.55x
with it at any ``block >= 64`` (the ISSUE 12 acceptance bound; the
per-dtype gauges ``comm/collective_bytes_{int8,f32}`` make the split
readable straight off the registry). Error per element is bounded by
one quantization step per hop plus one for the gather —
``<= (N) · amax_block / 254`` worst case, and in practice far below
it because partial sums concentrate (tests/test_qcomm.py pins the
bound and the loss-curve parity).

Integration: ``dp_grad_comm="int8"`` on ``HybridParallelTrainer``
(strategy_compiler.py) and ``HybridPipelineTrainer`` (hybrid.py).
Because GSPMD keeps the DP AllReduce *implicit* (mean loss over a
dp-sharded batch), the quantized path needs the pre-reduction
gradients — the trainers wrap the loss/grad computation in an
all-manual ``shard_map`` over the mesh, compute per-shard local
gradients, and reduce them through ``quantized_all_reduce_tree``
(one fused ring over the concatenated gradient buffer, the EQuARX
fused-buffer layout). Supported for pure data parallelism
(every non-dp mesh axis must be size 1) — composing with tp/pp is
ROADMAP residue.

All ops are plain jax collectives (``ppermute`` / ``all_gather``), so
the XLA graph is what runs on TPU — no host round-trip, and the
profiler's HLO byte accounting sees the real int8 payloads.

ZeRO composition (ISSUE 19; Xu et al., "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", 2004.13336):
the ring's reduce-scatter half IS ZeRO's gradient sharding, so the
AllReduce is split into standalone :func:`quantized_reduce_scatter`
(shard r ends owning the fully-reduced flat chunk r) +
:func:`quantized_all_gather`, each with an f32 spelling
(``reduce_scatter`` / ``all_gather_cast``). :func:`dp_zero_step` is
the ONE shard_map wrap both trainers use for the sharded weight
update: reduce-scatter grads → clip/guard on the REDUCED shard →
shard-local elementwise optimizer update (state at chunk shape — the
memory win) → all-gather the updated params (``dp_param_comm`` picks
the f32/bf16/int8 return payload).
"""
from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["quantize_blockwise", "dequantize_blockwise",
           "quantized_all_reduce", "quantized_all_reduce_tree",
           "quantized_reduce_scatter", "quantized_all_gather",
           "reduce_scatter", "all_gather_cast", "zero_chunk_len",
           "dp_zero_step", "validate_dp_grad_comm",
           "validate_dp_param_comm", "dp_batch_specs"]


def validate_dp_grad_comm(dp_grad_comm: str, mesh, *, zero_stage: int = 0,
                          block: int = 2048, unsupported=()) -> None:
    """The ONE validation of the trainers' ``dp_grad_comm`` knob
    (strategy_compiler.HybridParallelTrainer and
    hybrid.HybridPipelineTrainer share it so the constraints cannot
    drift): value in {'f32', 'int8'}; 'int8' additionally requires a
    positive block size, a pure-DP mesh (every non-dp axis size 1),
    ZeRO stage <= 2 (stages 1-2 ride the ring's reduce-scatter half;
    stage 3 is residue), and none of the caller's ``unsupported``
    (name, flag) feature pairs."""
    if dp_grad_comm not in ("f32", "int8"):
        raise ValueError(
            f"unknown dp_grad_comm {dp_grad_comm!r}; expected "
            "'f32' or 'int8'")
    if dp_grad_comm != "int8":
        return
    if block < 1:
        raise ValueError("dp_grad_block must be >= 1")
    other = {a: s for a, s in mesh.shape.items()
             if a != "dp" and s > 1}
    if other:
        raise NotImplementedError(
            f"dp_grad_comm='int8' supports pure data parallelism; "
            f"mesh has non-dp axes {other} (quantized collectives "
            "under tp/pp/sp are ROADMAP residue)")
    if zero_stage >= 3:
        raise NotImplementedError(
            "dp_grad_comm='int8' with ZeRO stage 3 (parameter "
            "sharding) is ROADMAP residue; stages 1-2 run the "
            "sharded weight update on the quantized ring")
    for name, flag in unsupported:
        if flag:
            raise NotImplementedError(
                f"dp_grad_comm='int8' does not compose with {name}")


def validate_dp_param_comm(dp_param_comm: str, zero_manual: bool) -> None:
    """Validation of the trainers' ``dp_param_comm`` knob (the
    all-gather payload of the ZeRO return half): value in
    {'f32', 'bf16', 'int8'}; the compressed spellings only mean
    anything on the manual sharded-update path."""
    if dp_param_comm not in ("f32", "bf16", "int8"):
        raise ValueError(
            f"unknown dp_param_comm {dp_param_comm!r}; expected "
            "'f32', 'bf16' or 'int8'")
    if dp_param_comm != "f32" and not zero_manual:
        raise ValueError(
            f"dp_param_comm={dp_param_comm!r} requires the manual "
            "ZeRO sharded update (zero_stage 1/2 on a pure-DP mesh "
            "with dp > 1); without it params never ride a collective")


def dp_quantized_value_and_grads(mesh, axis_size: int, block: int,
                                 fn, rep_args, batch, batch_specs,
                                 key):
    """THE quantized-DP shard_map wrap, shared by both trainers (like
    ``validate_dp_grad_comm``, so the semantics cannot drift):
    ``fn(rep_args, key, batch) -> (loss, aux, grads)`` runs once per
    dp shard inside an all-manual shard_map — replicated ``rep_args``,
    per-leaf-sharded ``batch``, the rng key folded with the shard
    index so dropout masks stay independent — and the reductions are
    pmean for the loss and floating ``aux`` leaves (non-float aux
    passes through: identical across shards by construction) and the
    quantized ring (mean) for ``grads``. Returns the reduced
    (loss, aux, grads)."""
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    def body(rep, key_, *batch_):
        key_ = jax.random.fold_in(key_, jax.lax.axis_index("dp"))
        loss, aux, grads = fn(rep, key_, batch_)
        loss = jax.lax.pmean(loss, "dp")
        aux = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "dp")
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
            else a, aux)
        grads = quantized_all_reduce_tree(grads, "dp", axis_size,
                                          block=block, mean=True)
        return loss, aux, grads

    return shard_map(body, mesh=mesh,
                     in_specs=(P(), P()) + tuple(batch_specs),
                     out_specs=(P(), P(), P()),
                     check_vma=False)(rep_args, key, *batch)


def dp_batch_specs(batch, dp: int):
    """Per-leaf shard_map in_specs for a batch tuple under the
    quantized-DP wrap (the no-``data_spec`` default). Under GSPMD,
    sharding any leaf's dim 0 is layout-only; under the MANUAL wrap a
    split is semantic — each shard computes on its slice — so only
    leaves that actually ride the batch axis may be split: dim 0 must
    equal the FIRST array leaf's dim 0 (the batch size — labels/aux
    inputs ride dim-0-aligned with the first, the ``tokens_in_batch``
    convention) and divide ``dp``. Everything else (masks, position
    vectors, scalars) replicates; an indivisible batch replicates
    everything, which degrades to every shard computing the full batch
    — wasteful but exact."""
    from jax.sharding import PartitionSpec as P

    lead = next((b.shape[0] for b in batch
                 if getattr(b, "ndim", 0) >= 1), None)
    if lead is None or lead % dp:
        return tuple(P() for _ in batch)
    return tuple(
        P("dp") if getattr(b, "ndim", 0) >= 1 and b.shape[0] == lead
        else P()
        for b in batch)

#: symmetric int8 range used for every payload (round-to-nearest-even
#: via jnp.round, the repo's int8_matmul convention)
_QMAX = 127.0


def quantize_blockwise(x: jax.Array, block: int = 2048
                       ) -> Tuple[jax.Array, jax.Array]:
    """Flat f32 vector (length divisible by ``block``) -> (int8 values,
    f32 per-block scales ``amax/127``). An all-zero block gets scale 0
    and quantizes to exact zeros (the null-block analogue of the KV
    pool's null-page scale)."""
    xb = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(xb), axis=1) / _QMAX
    q = jnp.round(xb / jnp.maximum(scale, 1e-30)[:, None])
    q = jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)
    return q.reshape(-1), scale


def dequantize_blockwise(q: jax.Array, scale: jax.Array,
                         block: int = 2048) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (f32 out)."""
    return (q.reshape(-1, block).astype(jnp.float32)
            * scale[:, None]).reshape(-1)


def _chunk(chunks: jax.Array, idx) -> jax.Array:
    return jax.lax.dynamic_index_in_dim(chunks, idx, axis=0,
                                        keepdims=False)


def zero_chunk_len(total: int, axis_size: int, block: int) -> int:
    """Per-shard flat chunk length of the ZeRO/ring layout: ``total``
    elements split into one chunk per shard, each a whole number of
    quantization blocks. Callers pad their flat buffer to
    ``axis_size * zero_chunk_len(...)``."""
    return block * max(1, math.ceil(total / (axis_size * block)))


def quantized_reduce_scatter(x: jax.Array, axis_name: str,
                             axis_size: int, *, block: int = 2048,
                             mean: bool = False) -> jax.Array:
    """The quantized ring's reduce-scatter half, standalone (ZeRO's
    gradient sharding). ``x`` is the per-shard flat f32 buffer, padded
    to ``axis_size * chunk`` with ``chunk`` a multiple of ``block``
    (:func:`zero_chunk_len`); the return is the fully-reduced f32
    chunk THIS shard owns — shard ``r`` owns ``x[r*chunk:(r+1)*chunk]``
    — after ``axis_size - 1`` int8 ``ppermute`` hops with f32
    accumulation. Must run inside a shard_map manual over
    ``axis_name``."""
    n = int(axis_size)
    if n < 1:
        raise ValueError(f"axis_size must be >= 1, got {n}")
    if block < 1:
        raise ValueError(f"block must be >= 1, got {block}")
    flat = x.astype(jnp.float32).reshape(-1)
    if n == 1:
        return flat / n if mean else flat
    if flat.shape[0] % (n * block):
        raise ValueError(
            f"reduce-scatter input size {flat.shape[0]} must be a "
            f"multiple of axis_size*block = {n * block}; pad to "
            "zero_chunk_len first")
    chunks = flat.reshape(n, -1)
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Ring reduce-scatter, int8 hops / f32 accumulation. Start one
    # chunk BEHIND the owned index so that after n-1 forward hops the
    # partial lands home: device r seeds chunk r-1, and at hop s adds
    # its own contribution to the incoming partial of chunk r-2-s;
    # after the last hop (s = n-2) it holds the full sum of chunk r.
    acc = _chunk(chunks, jnp.mod(r - 1, n))
    for s in range(n - 1):
        q, sc = quantize_blockwise(acc, block)
        q = jax.lax.ppermute(q, axis_name, perm)
        sc = jax.lax.ppermute(sc, axis_name, perm)
        acc = dequantize_blockwise(q, sc, block) \
            + _chunk(chunks, jnp.mod(r - 2 - s, n))
    return acc / n if mean else acc


def reduce_scatter(x: jax.Array, axis_name: str, axis_size: int, *,
                   mean: bool = False) -> jax.Array:
    """f32 spelling of :func:`quantized_reduce_scatter`: the same ring
    (same ownership — shard r gets chunk r — and the same pairwise f32
    accumulation order) with uncompressed hops. Input must be padded
    to a multiple of ``axis_size``."""
    n = int(axis_size)
    if n < 1:
        raise ValueError(f"axis_size must be >= 1, got {n}")
    flat = x.astype(jnp.float32).reshape(-1)
    if n == 1:
        return flat / n if mean else flat
    if flat.shape[0] % n:
        raise ValueError(
            f"reduce-scatter input size {flat.shape[0]} must be a "
            f"multiple of axis_size {n}")
    chunks = flat.reshape(n, -1)
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc = _chunk(chunks, jnp.mod(r - 1, n))
    for s in range(n - 1):
        acc = jax.lax.ppermute(acc, axis_name, perm) \
            + _chunk(chunks, jnp.mod(r - 2 - s, n))
    return acc / n if mean else acc


def quantized_all_gather(chunk: jax.Array, axis_name: str, *,
                         block: int = 2048) -> jax.Array:
    """The quantized ring's all-gather half, standalone (ZeRO's param
    return): each shard quantizes its owned chunk once, all-gathers
    int8 + scales, and dequantizes locally. Because shard r owns chunk
    r, gathered row order IS chunk order — the flat f32 concatenation
    comes back directly."""
    q, sc = quantize_blockwise(chunk.astype(jnp.float32), block)
    qg = jax.lax.all_gather(q, axis_name, axis=0)
    sg = jax.lax.all_gather(sc, axis_name, axis=0)
    return (qg.reshape(qg.shape[0], -1, block).astype(jnp.float32)
            * sg[:, :, None]).reshape(-1)


def all_gather_cast(chunk: jax.Array, axis_name: str,
                    dtype=jnp.float32) -> jax.Array:
    """Uncompressed spelling of :func:`quantized_all_gather`: gather
    the owned chunk cast to ``dtype`` for transport (``bf16`` halves
    the payload at ~3 significand decimal digits; ``f32`` is exact)
    and return the flat f32 concatenation."""
    g = jax.lax.all_gather(chunk.astype(dtype), axis_name, axis=0)
    return g.astype(jnp.float32).reshape(-1)


def quantized_all_reduce(x: jax.Array, axis_name: str, axis_size: int,
                         *, block: int = 2048,
                         mean: bool = False) -> jax.Array:
    """EQuARX-style compressed AllReduce of ``x`` over ``axis_name``.

    Must run inside a ``shard_map`` region manual over ``axis_name``
    (``axis_size`` is the static axis size — the ring unrolls
    ``axis_size - 1`` hops at trace time). Transport is blockwise int8
    with f32 block scales; accumulation is f32; the result is
    replicated across the axis. ``mean=True`` divides by the axis size
    (the DP-gradient convention). Output keeps ``x``'s shape/dtype.

    Spelled as the composition of the standalone ring halves:
    :func:`quantized_reduce_scatter` then :func:`quantized_all_gather`
    (the ZeRO split of ISSUE 19 — an AllReduce is exactly the two
    halves back to back with no compute between).
    """
    n = int(axis_size)
    if n < 1:
        raise ValueError(f"axis_size must be >= 1, got {n}")
    if block < 1:
        raise ValueError(f"block must be >= 1, got {block}")
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    if n == 1:
        return (flat / n if mean else flat).reshape(orig_shape) \
            .astype(orig_dtype)
    size = flat.shape[0]
    chunk = zero_chunk_len(size, n, block)
    flat = jnp.pad(flat, (0, chunk * n - size))
    acc = quantized_reduce_scatter(flat, axis_name, n, block=block,
                                   mean=mean)
    full = quantized_all_gather(acc, axis_name, block=block)[:size]
    return full.reshape(orig_shape).astype(orig_dtype)


def quantized_all_reduce_tree(tree, axis_name: str, axis_size: int,
                              *, block: int = 2048, mean: bool = False):
    """:func:`quantized_all_reduce` over a whole gradient pytree as ONE
    fused ring (EQuARX's fused-buffer layout: one concatenated flat
    buffer -> one reduce-scatter + one all-gather instead of a
    collective per leaf). Leaves are cast to f32 for transport and
    restored to their own shapes/dtypes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    flat = jnp.concatenate(
        [jnp.asarray(l).astype(jnp.float32).reshape(-1) for l in leaves])
    red = quantized_all_reduce(flat, axis_name, axis_size, block=block,
                               mean=mean)
    out, off = [], 0
    for l in leaves:
        sz = int(jnp.size(l))
        out.append(red[off:off + sz].reshape(jnp.shape(l))
                   .astype(jnp.asarray(l).dtype))
        off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


def dp_zero_step(mesh, axis_size: int, block: int, grad_comm: str,
                 param_comm: str, fn, update_fn, rep_args, params,
                 flat_state, batch, batch_specs, key, lr, step_no,
                 plr, wd, *, clip_norm=None, guard: bool = False):
    """THE ZeRO sharded-weight-update shard_map wrap, shared by both
    trainers (like ``dp_quantized_value_and_grads``, so the semantics
    cannot drift). One manual region over ``dp`` does the whole step:

    1. ``fn(rep_args, params, key, batch) -> (loss, aux, grads)`` runs
       per shard on its batch slice (key folded with the shard index);
       loss and floating ``aux`` leaves are pmean'd.
    2. Gradients are flattened into ONE fused f32 buffer (EQuARX
       layout), padded to ``axis_size * chunk``
       (:func:`zero_chunk_len`), and reduce-scattered (mean) to their
       owner shard — the quantized ring for ``grad_comm='int8'``, the
       f32 ring otherwise. Per-replica transient grad memory after
       this point is ``chunk``, not ``total``.
    3. Global-norm clipping (when ``clip_norm`` is set) via a psum of
       per-shard squared chunk sums — mathematically the full-tensor
       norm, computed without regathering.
    4. ``guard=True`` computes the bad-step verdict HERE, on the
       reduced shard grads + pmean'd loss, and pmin-agrees it across
       the mesh so every shard takes the identical keep/skip branch.
    5. ``update_fn(p_chunk, g_chunk, moments, lr, step_no, plr, wd)
       -> (new_p_chunk, new_moments)`` runs shard-locally on the owned
       flat slice. The parameter chunk comes from ``flat_state
       ['master']`` when present (the f32 master copy required for
       compressed ``param_comm`` — bf16 round-trip rounding would
       swallow small updates), else it is sliced out of the replicated
       params. Optimizer state lives at chunk shape: the memory win.
    6. A guarded-bad step deselects the NEW state bitwise (moments and
       master keep their previous values).
    7. The updated chunk all-gathers back — f32 exact, bf16 cast, or
       the quantized gather per ``param_comm`` — and leaves are
       restored to their shapes/dtypes; on a guarded-bad step every
       leaf reverts bitwise to its input value (the deselect happens
       AFTER the gather, so compressed-payload garbage from a NaN step
       is discarded, never applied).

    ``plr`` / ``wd`` are per-parameter learning-rate multipliers /
    weight-decay factors: scalars when uniform, else flat
    ``axis_size * chunk`` f32 vectors laid out exactly like the fused
    param buffer (they enter the shard_map with spec ``P('dp')`` and
    arrive pre-sliced to the owned chunk).

    Returns ``(loss, aux, new_params, new_flat_state)`` plus the
    mesh-agreed ``ok`` bool when ``guard``. ``new_flat_state`` keeps
    the dp-sharded layout (out_spec ``P('dp')``); everything else is
    replicated.
    """
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    n = int(axis_size)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    sizes = [int(jnp.size(l)) for l in leaves]
    total = sum(sizes)
    chunk = zero_chunk_len(total, n, block)
    pad = chunk * n - total

    def _knob_spec(v):
        return P("dp") if getattr(v, "ndim", 0) >= 1 else P()

    def body(rep, params_, state, key_, lr_, step_no_, plr_, wd_,
             *batch_):
        key_ = jax.random.fold_in(key_, jax.lax.axis_index("dp"))
        loss, aux, grads = fn(rep, params_, key_, batch_)
        loss = jax.lax.pmean(loss, "dp")
        aux = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "dp")
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
            else a, aux)

        gleaves = jax.tree_util.tree_leaves(grads)
        flat_g = jnp.concatenate(
            [g.astype(jnp.float32).reshape(-1) for g in gleaves])
        flat_g = jnp.pad(flat_g, (0, pad))
        if grad_comm == "int8":
            g_c = quantized_reduce_scatter(flat_g, "dp", n, block=block,
                                           mean=True)
        else:
            g_c = reduce_scatter(flat_g, "dp", n, mean=True)

        if clip_norm is not None:
            gsq = jax.lax.psum(jnp.sum(jnp.square(g_c)), "dp")
            gn = jnp.sqrt(gsq)
            g_c = g_c * jnp.where(gn > clip_norm, clip_norm / gn, 1.0)

        ok = None
        if guard:
            ok_local = jnp.logical_and(
                jnp.isfinite(loss), jnp.all(jnp.isfinite(g_c)))
            ok = jax.lax.pmin(ok_local.astype(jnp.int32), "dp") \
                .astype(jnp.bool_)

        pleaves = jax.tree_util.tree_leaves(params_)
        if "master" in state:
            p_c = state["master"]
        else:
            flat_p = jnp.concatenate(
                [p0.astype(jnp.float32).reshape(-1) for p0 in pleaves])
            flat_p = jnp.pad(flat_p, (0, pad))
            r = jax.lax.axis_index("dp")
            p_c = jax.lax.dynamic_slice(flat_p, (r * chunk,), (chunk,))
        moments = {k: v for k, v in state.items() if k != "master"}
        new_p_c, new_moments = update_fn(p_c, g_c, moments, lr_,
                                         step_no_, plr_, wd_)
        new_state = dict(new_moments)
        if "master" in state:
            new_state["master"] = new_p_c
        if ok is not None:
            new_state = {k: jnp.where(ok, v, state[k])
                         for k, v in new_state.items()}

        if param_comm == "int8":
            full = quantized_all_gather(new_p_c, "dp", block=block)
        elif param_comm == "bf16":
            full = all_gather_cast(new_p_c, "dp", jnp.bfloat16)
        else:
            full = all_gather_cast(new_p_c, "dp", jnp.float32)
        out_leaves, off = [], 0
        for p0, sz in zip(pleaves, sizes):
            nl = full[off:off + sz].reshape(p0.shape).astype(p0.dtype)
            if ok is not None:
                nl = jnp.where(ok, nl, p0)
            out_leaves.append(nl)
            off += sz
        new_params = jax.tree_util.tree_unflatten(treedef, out_leaves)
        if guard:
            return loss, aux, new_params, new_state, ok
        return loss, aux, new_params, new_state

    in_specs = (P(), P(), P("dp"), P(), P(), P(),
                _knob_spec(plr), _knob_spec(wd)) + tuple(batch_specs)
    out_specs = (P(), P(), P(), P("dp"))
    if guard:
        out_specs = out_specs + (P(),)
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)(
        rep_args, params, flat_state, key, lr, step_no, plr, wd,
        *batch)
