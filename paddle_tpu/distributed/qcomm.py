"""Quantized collectives: EQuARX-style compressed AllReduce for the
DP gradient path (ROADMAP item 3b; "EQuARX: Efficient Quantized
AllReduce in XLA", PAPERS.md).

A data-parallel step moves every gradient byte across the mesh once
per step, and on multi-host meshes that AllReduce IS the comm phase
the profiler accounts (``comm/collective_bytes_per_step``). EQuARX's
observation: the reduction tolerates low-precision *transport* as long
as *accumulation* stays high-precision — so quantize each hop of the
ring, not the math:

1. **Blockwise int8 quantization.** The flat gradient is cut into
   ``block``-element blocks; each block ships as int8 with one f32
   scale (``amax / 127``). Per-block scaling is what makes one outlier
   cost one block's precision instead of the whole tensor's (the same
   reasoning as the per-page per-head KV scales in
   ``serving/paged_cache.py`` and the per-tensor amax idiom of
   ``ops/int8_matmul.py``).
2. **Reduce-scatter in low precision, accumulate in f32.** A classic
   ring reduce-scatter (``N - 1`` ``ppermute`` hops) where every hop's
   payload is the quantized partial sum + its block scales; the
   receiver dequantizes, adds its own f32 shard, and re-quantizes for
   the next hop. Wire bytes per hop: ``T/N`` int8 + ``T/(N·block)``
   f32 scales, vs ``4·T/N`` for the f32 ring.
3. **Quantized all-gather.** Each device quantizes its fully-reduced
   shard once and all-gathers int8 + scales; everyone dequantizes
   locally.

Counted result-buffer bytes (what ``profiler.collective_stats``
measures): ``(N-1)/N·T + T`` int8 + scale overhead ≈ ``2T`` bytes vs
the f32 AllReduce's ``4T`` — ≤ 0.5x before scale overhead, ≤ 0.55x
with it at any ``block >= 64`` (the ISSUE 12 acceptance bound; the
per-dtype gauges ``comm/collective_bytes_{int8,f32}`` make the split
readable straight off the registry). Error per element is bounded by
one quantization step per hop plus one for the gather —
``<= (N) · amax_block / 254`` worst case, and in practice far below
it because partial sums concentrate (tests/test_qcomm.py pins the
bound and the loss-curve parity).

Integration: ``dp_grad_comm="int8"`` on ``HybridParallelTrainer``
(strategy_compiler.py) and ``HybridPipelineTrainer`` (hybrid.py).
Because GSPMD keeps the DP AllReduce *implicit* (mean loss over a
dp-sharded batch), the quantized path needs the pre-reduction
gradients — the trainers wrap the loss/grad computation in an
all-manual ``shard_map`` over the mesh, compute per-shard local
gradients, and reduce them through ``quantized_all_reduce_tree``
(one fused ring over the concatenated gradient buffer, the EQuARX
fused-buffer layout). Supported for pure data parallelism
(every non-dp mesh axis must be size 1, no ZeRO) — composing with
tp/pp/sharded optimizer state is ROADMAP residue.

All ops are plain jax collectives (``ppermute`` / ``all_gather``), so
the XLA graph is what runs on TPU — no host round-trip, and the
profiler's HLO byte accounting sees the real int8 payloads.
"""
from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["quantize_blockwise", "dequantize_blockwise",
           "quantized_all_reduce", "quantized_all_reduce_tree",
           "validate_dp_grad_comm", "dp_batch_specs"]


def validate_dp_grad_comm(dp_grad_comm: str, mesh, *, zero_stage: int = 0,
                          block: int = 2048, unsupported=()) -> None:
    """The ONE validation of the trainers' ``dp_grad_comm`` knob
    (strategy_compiler.HybridParallelTrainer and
    hybrid.HybridPipelineTrainer share it so the constraints cannot
    drift): value in {'f32', 'int8'}; 'int8' additionally requires a
    positive block size, a pure-DP mesh (every non-dp axis size 1),
    no ZeRO, and none of the caller's ``unsupported`` (name, flag)
    feature pairs."""
    if dp_grad_comm not in ("f32", "int8"):
        raise ValueError(
            f"unknown dp_grad_comm {dp_grad_comm!r}; expected "
            "'f32' or 'int8'")
    if dp_grad_comm != "int8":
        return
    if block < 1:
        raise ValueError("dp_grad_block must be >= 1")
    other = {a: s for a, s in mesh.shape.items()
             if a != "dp" and s > 1}
    if other:
        raise NotImplementedError(
            f"dp_grad_comm='int8' supports pure data parallelism; "
            f"mesh has non-dp axes {other} (quantized collectives "
            "under tp/pp/sp are ROADMAP residue)")
    if zero_stage:
        raise NotImplementedError(
            "dp_grad_comm='int8' with ZeRO sharding is ROADMAP "
            "residue (the quantized reduce-scatter half maps onto "
            "ZeRO's grad sharding but is not wired)")
    for name, flag in unsupported:
        if flag:
            raise NotImplementedError(
                f"dp_grad_comm='int8' does not compose with {name}")


def dp_quantized_value_and_grads(mesh, axis_size: int, block: int,
                                 fn, rep_args, batch, batch_specs,
                                 key):
    """THE quantized-DP shard_map wrap, shared by both trainers (like
    ``validate_dp_grad_comm``, so the semantics cannot drift):
    ``fn(rep_args, key, batch) -> (loss, aux, grads)`` runs once per
    dp shard inside an all-manual shard_map — replicated ``rep_args``,
    per-leaf-sharded ``batch``, the rng key folded with the shard
    index so dropout masks stay independent — and the reductions are
    pmean for the loss and floating ``aux`` leaves (non-float aux
    passes through: identical across shards by construction) and the
    quantized ring (mean) for ``grads``. Returns the reduced
    (loss, aux, grads)."""
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    def body(rep, key_, *batch_):
        key_ = jax.random.fold_in(key_, jax.lax.axis_index("dp"))
        loss, aux, grads = fn(rep, key_, batch_)
        loss = jax.lax.pmean(loss, "dp")
        aux = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "dp")
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
            else a, aux)
        grads = quantized_all_reduce_tree(grads, "dp", axis_size,
                                          block=block, mean=True)
        return loss, aux, grads

    return shard_map(body, mesh=mesh,
                     in_specs=(P(), P()) + tuple(batch_specs),
                     out_specs=(P(), P(), P()),
                     check_vma=False)(rep_args, key, *batch)


def dp_batch_specs(batch, dp: int):
    """Per-leaf shard_map in_specs for a batch tuple under the
    quantized-DP wrap (the no-``data_spec`` default). Under GSPMD,
    sharding any leaf's dim 0 is layout-only; under the MANUAL wrap a
    split is semantic — each shard computes on its slice — so only
    leaves that actually ride the batch axis may be split: dim 0 must
    equal the FIRST array leaf's dim 0 (the batch size — labels/aux
    inputs ride dim-0-aligned with the first, the ``tokens_in_batch``
    convention) and divide ``dp``. Everything else (masks, position
    vectors, scalars) replicates; an indivisible batch replicates
    everything, which degrades to every shard computing the full batch
    — wasteful but exact."""
    from jax.sharding import PartitionSpec as P

    lead = next((b.shape[0] for b in batch
                 if getattr(b, "ndim", 0) >= 1), None)
    if lead is None or lead % dp:
        return tuple(P() for _ in batch)
    return tuple(
        P("dp") if getattr(b, "ndim", 0) >= 1 and b.shape[0] == lead
        else P()
        for b in batch)

#: symmetric int8 range used for every payload (round-to-nearest-even
#: via jnp.round, the repo's int8_matmul convention)
_QMAX = 127.0


def quantize_blockwise(x: jax.Array, block: int = 2048
                       ) -> Tuple[jax.Array, jax.Array]:
    """Flat f32 vector (length divisible by ``block``) -> (int8 values,
    f32 per-block scales ``amax/127``). An all-zero block gets scale 0
    and quantizes to exact zeros (the null-block analogue of the KV
    pool's null-page scale)."""
    xb = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(xb), axis=1) / _QMAX
    q = jnp.round(xb / jnp.maximum(scale, 1e-30)[:, None])
    q = jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)
    return q.reshape(-1), scale


def dequantize_blockwise(q: jax.Array, scale: jax.Array,
                         block: int = 2048) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (f32 out)."""
    return (q.reshape(-1, block).astype(jnp.float32)
            * scale[:, None]).reshape(-1)


def _chunk(chunks: jax.Array, idx) -> jax.Array:
    return jax.lax.dynamic_index_in_dim(chunks, idx, axis=0,
                                        keepdims=False)


def quantized_all_reduce(x: jax.Array, axis_name: str, axis_size: int,
                         *, block: int = 2048,
                         mean: bool = False) -> jax.Array:
    """EQuARX-style compressed AllReduce of ``x`` over ``axis_name``.

    Must run inside a ``shard_map`` region manual over ``axis_name``
    (``axis_size`` is the static axis size — the ring unrolls
    ``axis_size - 1`` hops at trace time). Transport is blockwise int8
    with f32 block scales; accumulation is f32; the result is
    replicated across the axis. ``mean=True`` divides by the axis size
    (the DP-gradient convention). Output keeps ``x``'s shape/dtype.
    """
    n = int(axis_size)
    if n < 1:
        raise ValueError(f"axis_size must be >= 1, got {n}")
    if block < 1:
        raise ValueError(f"block must be >= 1, got {block}")
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    if n == 1:
        return (flat / n if mean else flat).reshape(orig_shape) \
            .astype(orig_dtype)
    size = flat.shape[0]
    # one chunk per device, each a whole number of blocks
    chunk = block * max(1, math.ceil(size / (n * block)))
    flat = jnp.pad(flat, (0, chunk * n - size))
    chunks = flat.reshape(n, chunk)
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # ring reduce-scatter, int8 hops / f32 accumulation: after n-1
    # hops device r holds the full sum of chunk (r + 1) % n
    acc = _chunk(chunks, r)
    for s in range(n - 1):
        q, sc = quantize_blockwise(acc, block)
        q = jax.lax.ppermute(q, axis_name, perm)
        sc = jax.lax.ppermute(sc, axis_name, perm)
        acc = dequantize_blockwise(q, sc, block) \
            + _chunk(chunks, jnp.mod(r - 1 - s, n))

    # quantized all-gather of the reduced shards; gathered row d is
    # chunk (d + 1) % n, so roll by one to restore chunk order
    q, sc = quantize_blockwise(acc, block)
    qg = jax.lax.all_gather(q, axis_name, axis=0)
    sg = jax.lax.all_gather(sc, axis_name, axis=0)
    full = (qg.reshape(n, -1, block).astype(jnp.float32)
            * sg[:, :, None]).reshape(n, chunk)
    full = jnp.roll(full, 1, axis=0).reshape(-1)[:size]
    if mean:
        full = full / n
    return full.reshape(orig_shape).astype(orig_dtype)


def quantized_all_reduce_tree(tree, axis_name: str, axis_size: int,
                              *, block: int = 2048, mean: bool = False):
    """:func:`quantized_all_reduce` over a whole gradient pytree as ONE
    fused ring (EQuARX's fused-buffer layout: one concatenated flat
    buffer -> one reduce-scatter + one all-gather instead of a
    collective per leaf). Leaves are cast to f32 for transport and
    restored to their own shapes/dtypes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    flat = jnp.concatenate(
        [jnp.asarray(l).astype(jnp.float32).reshape(-1) for l in leaves])
    red = quantized_all_reduce(flat, axis_name, axis_size, block=block,
                               mean=mean)
    out, off = [], 0
    for l in leaves:
        sz = int(jnp.size(l))
        out.append(red[off:off + sz].reshape(jnp.shape(l))
                   .astype(jnp.asarray(l).dtype))
        off += sz
    return jax.tree_util.tree_unflatten(treedef, out)
