"""Distributed environment bootstrap.

TPU-native analogue of the reference's process bring-up:
  - launcher env protocol  PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS
    (reference: distributed/fleet/launch_utils.py:457-464)
  - NCCL id TCP exchange   (reference: platform/gen_comm_id_helper.cc:208-319)
  - init_parallel_env      (reference: python/paddle/distributed/parallel.py:57)

On TPU all of this maps to jax.distributed.initialize: the coordination
service replaces the raw-TCP ncclUniqueId exchange, and the 'ring' concept
becomes mesh axes (SURVEY.md §5 backend translation).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def world_size(self) -> int:
        return jax.process_count()

    @property
    def device_id(self) -> int:
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    @property
    def current_endpoint(self) -> str:
        eps = self.trainer_endpoints
        return eps[self.rank] if self.rank < len(eps) else ""

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """paddle.distributed.init_parallel_env equivalent.

    Reads the PADDLE_* env protocol when explicit args are absent, then
    brings up the jax coordination service (multi-host). Single-process is a
    no-op (the one jax runtime already sees all local devices).
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    n = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n > 1:
        pid = process_id if process_id is not None else \
            int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        coord = coordinator_address or os.environ.get(
            "PADDLE_COORDINATOR", None)
        if coord is None:
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
            coord = eps[0] if eps and eps[0] else "127.0.0.1:12355"
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid)
    _initialized = True
    return ParallelEnv()


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def is_initialized() -> bool:
    return _initialized
