"""Collective communication python API
(reference: python/paddle/distributed/collective.py:101-457 — broadcast/
all_reduce/reduce/all_gather/scatter/barrier; C++ data plane
operators/collective/c_allreduce_op.h:157 etc.).

Two execution regimes, matching how the reference's ops were used:

1. **Eager / host regime** (this module's functions): cross-*process*
   collectives over the jax coordination service
   (multihost_utils) — the analogue of the reference's dygraph
   `core.ops.c_allreduce_sum` calls on the NCCL communicator. With one
   process they degenerate to identity, like a 1-rank ring.

2. **Compiled / SPMD regime**: inside pjit/shard_map, use
   paddle_tpu.distributed.primitives (psum/all_gather/ppermute wrappers) —
   XLA emits the ICI collectives. This is where all performance-critical
   communication happens (SURVEY §5: "there is no role for a NCCL-like
   userspace library").
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


def _world() -> int:
    return jax.process_count()


def _allgather_np(arr: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None,
               use_calc_stream=True):
    """In-place all-reduce across processes (reference: c_allreduce_op.h)."""
    if _world() == 1:
        return tensor
    stacked = _allgather_np(tensor.numpy())
    if op == ReduceOp.SUM:
        out = stacked.sum(0)
    elif op == ReduceOp.MAX:
        out = stacked.max(0)
    elif op == ReduceOp.MIN:
        out = stacked.min(0)
    else:
        out = stacked.prod(0)
    tensor.set_value(out)
    return tensor


def all_gather(tensor_list: List[Tensor], tensor: Tensor, group=None,
               use_calc_stream=True):
    if _world() == 1:
        tensor_list.append(Tensor(tensor._value))
        return tensor_list
    stacked = _allgather_np(tensor.numpy())
    for i in range(stacked.shape[0]):
        tensor_list.append(Tensor(stacked[i]))
    return tensor_list


def broadcast(tensor: Tensor, src: int, group=None, use_calc_stream=True):
    if _world() == 1:
        return tensor
    stacked = _allgather_np(tensor.numpy())
    tensor.set_value(stacked[src])
    return tensor


def reduce(tensor: Tensor, dst: int, op=ReduceOp.SUM, group=None,
           use_calc_stream=True):
    all_reduce(tensor, op, group, use_calc_stream)
    return tensor


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None,
            use_calc_stream=True):
    if _world() == 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    if tensor_list is not None:
        full = np.stack([np.asarray(t) for t in tensor_list])
    else:
        full = np.zeros((_world(),) + tuple(tensor.shape),
                        tensor.numpy().dtype)
    stacked = _allgather_np(full)[src]
    tensor.set_value(stacked[jax.process_index()])
    return tensor


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None):
    full = np.stack([np.asarray(t) for t in tensor_list])
    if _world() > 1:
        full = _allgather_np(full).sum(0)
    tensor.set_value(full[jax.process_index()] if _world() > 1 else full[0])
    return tensor


def alltoall(in_tensor_list, out_tensor_list, group=None):
    if _world() == 1:
        out_tensor_list.extend(Tensor(t._value) for t in in_tensor_list)
        return out_tensor_list
    full = np.stack([np.asarray(t) for t in in_tensor_list])
    gathered = _allgather_np(full)  # [world, world, ...]
    me = jax.process_index()
    for r in range(_world()):
        out_tensor_list.append(Tensor(gathered[r, me]))
    return out_tensor_list


def send(tensor, dst=0, group=None, use_calc_stream=True):
    raise NotImplementedError(
        "eager p2p send/recv is served by the SPMD pipeline path "
        "(distributed.pipeline uses ppermute); host-level p2p is not needed "
        "on TPU.")


recv = send


def barrier(group=None):
    """reference: operators/collective/barrier_op."""
    if _world() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("paddle_tpu_barrier")


def get_group(id=0):  # noqa: A002
    return None


# --- Megatron-style parallel building block -------------------------------
def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split equivalent
    (reference: distributed/collective.py:566 — _parallel_linear /
    _parallel_embedding). On TPU this is subsumed by the first-class
    tensor-parallel layers; kept as the compatibility entry point."""
    from .parallel_layers import ColumnParallelLinear, ParallelEmbedding, \
        RowParallelLinear

    if operation == "linear":
        in_f, out_f = size
        if axis == 1 or axis == "column":
            layer = ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                         bias_attr=bias_attr,
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                      bias_attr=bias_attr)
        return layer(x)
    if operation == "embedding":
        vocab, dim = size
        layer = ParallelEmbedding(vocab, dim, weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"Unsupported split operation: {operation}")
