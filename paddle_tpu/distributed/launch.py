"""Distributed launcher CLI: ``python -m paddle_tpu.distributed.launch``.

TPU-native analogue of the reference launcher (reference:
python/paddle/distributed/fleet/launch.py:334 launch(),
launch_utils.py:435-464 start_local_trainers — subprocess per rank with
the PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS env protocol; watch_local_trainers +
terminate_local_procs:295 tear the job down on any failure).

Differences by design:
  - one process per HOST (jax owns all local chips; the reference's
    one-process-per-GPU with FLAGS_selected_gpus has no TPU meaning);
    --nproc_per_node exists for CPU-simulation tests and multi-process
    hosts.
  - rendezvous is the jax coordination service (env.py
    init_parallel_env), not a raw-TCP ncclUniqueId exchange.

Usage:
    python -m paddle_tpu.distributed.launch --nproc_per_node 2 train.py
    python -m paddle_tpu.distributed.launch --ips host1,host2 train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn one training process per rank with the "
                    "PADDLE_* env protocol")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="ranks to spawn on this node")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated node ips (multi-host)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--started_port", type=int, default=0,
                   help="base port for rank endpoints (0 = pick free)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank logs to <log_dir>/workerlog.<rank>")
    p.add_argument("--backend", type=str, default=None,
                   help="override JAX_PLATFORMS in children (e.g. cpu)")
    p.add_argument("--host_devices", type=int, default=0,
                   help="virtual CPU devices per rank (testing; sets "
                        "xla_force_host_platform_device_count)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_endpoints(ips: List[str], nproc: int, base_port: int
                          ) -> List[str]:
    """reference: launch.py get_cluster_from_args:172."""
    eps = []
    for ip in ips:
        for i in range(nproc):
            eps.append(f"{ip}:{base_port + i}")
    return eps


def start_local_trainers(args, endpoints: List[str]) -> List[subprocess.Popen]:
    """reference: launch_utils.py start_local_trainers:435."""
    procs = []
    nproc = args.nproc_per_node
    n_total = len(endpoints)
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local_rank in range(nproc):
        rank = args.node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(n_total),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_RANK_IN_NODE": str(local_rank),
            "PADDLE_COORDINATOR": endpoints[0],
        })
        if args.backend:
            env["JAX_PLATFORMS"] = args.backend
            env["PALLAS_AXON_POOL_IPS"] = ""
        if args.host_devices:
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.host_devices}").strip()
        cmd = [sys.executable, args.training_script] + \
            args.training_script_args
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir,
                                    f"workerlog.{rank}"), "w")
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT
            if out else None))
    return procs


def watch_local_trainers(procs: List[subprocess.Popen]) -> int:
    """Poll children; on any failure terminate the rest (reference:
    launch_utils.py watch_local_trainers + terminate_local_procs:295)."""
    try:
        while True:
            alive = False
            for p in procs:
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    terminate_local_procs(procs)
                    return rc
            if not alive:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        terminate_local_procs(procs)
        return 130


def terminate_local_procs(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.2)
        if p.poll() is None:
            p.kill()


def launch(argv=None) -> int:
    args = parse_args(argv)
    if args.training_script_args[:1] == ["--"]:
        args.training_script_args = args.training_script_args[1:]
    ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    base = args.started_port or _free_port()
    endpoints = get_cluster_endpoints(ips, args.nproc_per_node, base)
    procs = start_local_trainers(args, endpoints)
    return watch_local_trainers(procs)


if __name__ == "__main__":
    sys.exit(launch())
