"""Sharded, async, multi-host checkpointing.

Designed fresh for TPU (SURVEY.md §5 checkpoint/resume: the reference has
only synchronous save ops — operators/save_op.cc, fluid/io.py:621
save_persistables — and fleet's sharded save delegates to rank groups,
fleet_base.py:518-550; there is no optimizer-state sharded checkpoint
format for collective mode). Here:

  - **keyed by mesh shard**: every jax.Array in the state tree is saved as
    its device shards. Each process writes ONE shard file containing the
    shards it owns (``replica_id == 0`` dedupes replicas), so a save is
    embarrassingly parallel across hosts and never materializes a global
    array.
  - **async**: device→host copies happen inline (cheap, HBM→RAM), file
    writes stream through the native background writer
    (native/src/file_writer.cc, AsyncWriter) — training resumes while
    bytes hit disk. ``SaveHandle.wait()`` / ``CheckpointManager.wait()``
    joins, fsyncs, and commits.
  - **crash-consistent**: a step directory is only valid once its COMMIT
    marker exists; the marker is written after every writer has fsync'd
    (file + parent dir). ``latest_step`` ignores uncommitted directories,
    so a kill mid-save resumes from the previous step.
  - **resume-exact**: restore targets a template pytree (arrays or
    ShapeDtypeStructs carrying shardings). The fast path feeds each
    target shard straight from the matching saved shard (local reads
    only); a topology change falls back to assembling the global array.
  - metadata rides along (step, RNG key, data-pipeline cursor, anything
    JSON-serializable) for deterministic resume.

Layout::

    dir/step_00000100/
        shard_p0.bin manifest_p0.json   # per process
        meta.json COMMIT                # process 0
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core.native import AsyncWriter, available as _native_available

_STEP_FMT = "step_{:08d}"
_COMMIT = "COMMIT"

# streamed-snapshot default: one bounded D2H chunk at a time feeds the
# writer, so host RAM holds ~2 chunks of not-yet-written bytes instead
# of the whole state while copies overlap writes (and, via the
# SaveHandle.wait_snapshot gate, subsequent training-step dispatch).
_SNAPSHOT_CHUNK_BYTES = 64 * 1024 * 1024


def _ckpt_counters():
    """(stall_ms, d2h_bytes) counters — the async-snapshot win is
    MEASURED: stall_ms accumulates only time the training loop was
    actually blocked (the inline part of save() plus any
    wait_snapshot gate wait), d2h_bytes every device→host byte."""
    from ..profiler.metrics import registry

    reg = registry()
    return reg.counter("ckpt/stall_ms"), reg.counter("ckpt/d2h_bytes")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _dtype_name(dt) -> str:
    return str(np.dtype(dt)) if not str(dt).startswith("bfloat16") \
        else "bfloat16"


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _norm_index(index, shape) -> List[List[int]]:
    """Tuple-of-slices → [[start, stop], ...] on the global shape."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        assert step == 1, "strided shards unsupported"
        out.append([int(start), int(stop)])
    # index may be shorter than rank (trailing full dims)
    for dim in shape[len(out):]:
        out.append([0, int(dim)])
    return out


def _flatten(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(_path_elem(p) for p in path)
        out.append((key, leaf))
    return out


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


class _PyWriter:
    """Pure-python fallback for AsyncWriter (same contract)."""

    def __init__(self, path: str, depth: int = 8):
        self._f = open(path, "wb")
        self._total = 0
        self._crc = 0

    def write(self, data) -> None:
        import zlib

        b = memoryview(data).cast("B")
        self._f.write(b)
        self._crc = zlib.crc32(b, self._crc)
        self._total += len(b)

    def close(self):
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        return (self._total, self._crc)


def _open_writer(path: str):
    if _native_available():
        return AsyncWriter(path, depth=16)
    return _PyWriter(path)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


class SaveHandle:
    """In-flight async save. ``wait()`` blocks until the checkpoint is
    durable and (on process 0) committed.

    The cross-host barrier and the COMMIT marker happen inside ``wait()``
    on the CALLER's thread: a collective issued from a background thread
    could interleave with training collectives in different orders on
    different hosts and deadlock XLA."""

    def __init__(self, step_dir: str, step: int, thread: threading.Thread,
                 errbox: list, snap_event: Optional[threading.Event] = None):
        self._dir = step_dir
        self._step = step
        self._thread = thread
        self._err = errbox
        self._done = False
        # None: the snapshot (device→host copy) happened inline in
        # save(); an Event: the streamed-snapshot thread sets it once
        # every byte of device state has been copied to host.
        self._snap = snap_event

    @property
    def snapshot_done(self) -> bool:
        return self._snap is None or self._snap.is_set()

    def wait_snapshot(self) -> None:
        """Block until the device state is fully copied to host — the
        gate a training loop with DONATED state must pass before
        dispatching the next step (the step would otherwise invalidate
        the buffers the snapshot is still reading). File writes, fsync,
        and COMMIT continue in the background; only ``wait()`` joins
        those. The block time lands in ``ckpt/stall_ms``."""
        if self._snap is None or self._snap.is_set():
            return
        stall, _ = _ckpt_counters()
        t0 = time.perf_counter_ns()
        self._snap.wait()
        stall.add((time.perf_counter_ns() - t0) / 1e6)

    def wait(self) -> None:
        if self._done:
            return
        self.wait_snapshot()
        self._thread.join()
        self._done = True
        # exchange error status BEFORE committing: a host whose shard
        # write failed must veto the COMMIT on every host (otherwise
        # process 0 marks a step committed whose manifests are missing),
        # and the exchange itself keeps the hosts barrier-aligned even on
        # the error path.
        n_failed = _sum_across_hosts(1 if self._err else 0)
        if n_failed:
            if self._err:
                raise self._err[0]
            raise IOError(
                f"checkpoint step {self._step}: shard write failed on "
                f"{n_failed} host(s); step NOT committed")
        if jax.process_index() == 0:
            with open(os.path.join(self._dir, _COMMIT), "w") as f:
                f.write("ok\n")
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(self._dir)
        # all hosts agree the step is committed before anyone reads it
        _barrier(f"ckpt_commit_{self._step}")

    @property
    def directory(self) -> str:
        return self._dir


def save(directory: str, state, step: int, meta: Optional[dict] = None,
         async_: bool = True, snapshot_async: bool = False,
         snapshot_chunk_bytes: int = _SNAPSHOT_CHUNK_BYTES) -> SaveHandle:
    """Save a pytree of jax.Arrays as a sharded checkpoint.

    Returns a SaveHandle; the checkpoint is valid only after ``wait()``
    (CheckpointManager calls it for you at the next save/exit).

    snapshot_async=False (default): device→host copies happen inline —
    the call blocks for the full D2H of the owned shards (the measured
    ~5 s stall for a ~10 GiB state over a 2 GiB/s link) and the state
    may be mutated/donated the moment this returns.

    snapshot_async=True: the call returns after recording shard
    METADATA only; the device→host copies run on the background thread
    in bounded ``snapshot_chunk_bytes`` chunks (async host-copy
    lookahead of one chunk, each chunk fed straight to the writer), so
    the copy overlaps whatever the host does next — data fetch, H2D
    staging, loss sync. The caller MUST pass ``wait_snapshot()`` before
    re-dispatching a step that donates the saved arrays: a donation
    races the copy and fails the save loudly at ``wait()`` (never a
    silent half-state — COMMIT only lands after every byte + fsync).
    """
    proc = jax.process_index()
    nproc = jax.process_count()
    step_dir = os.path.join(directory, _STEP_FMT.format(step))
    os.makedirs(step_dir, exist_ok=True)
    # re-saving a step that was committed before (a rollback replay, or
    # a resumed run crossing its old save cadence): the stale COMMIT
    # must come off BEFORE any shard byte is rewritten, or a crash
    # mid-rewrite would leave a dir that latest_step trusts but whose
    # shards are half old, half new.
    commit_path = os.path.join(step_dir, _COMMIT)
    if proc == 0 and os.path.exists(commit_path):
        os.unlink(commit_path)
        _fsync_dir(step_dir)
    _barrier(f"ckpt_recommit_{step}")

    stall, d2h = _ckpt_counters()
    t0 = time.perf_counter_ns()
    # inline part: walk the owned shards. Sync mode copies each to host
    # right here (snapshot semantics — training may mutate device state
    # the moment this returns); async-snapshot mode records only the
    # (device_shard, nbytes) plan, metadata reads that never sync.
    entries: Dict[str, dict] = {}
    buffers: List[list] = []        # [shard_or_host, nbytes]
    offset = 0
    for key, arr in _flatten(state):
        if arr is None:
            continue
        arr = arr if isinstance(arr, jax.Array) else jax.numpy.asarray(arr)
        info = {"shape": [int(d) for d in arr.shape],
                "dtype": _dtype_name(arr.dtype), "shards": []}
        itemsize = jax.numpy.dtype(arr.dtype).itemsize
        for sh in arr.addressable_shards:
            if sh.replica_id != 0:
                continue
            if snapshot_async:
                data = sh.data
                nbytes = int(np.prod(data.shape)) * itemsize \
                    if data.shape else itemsize
            else:
                data = np.ascontiguousarray(np.asarray(sh.data))
                nbytes = data.nbytes
            info["shards"].append({
                "index": _norm_index(sh.index, arr.shape),
                "offset": offset, "nbytes": int(nbytes)})
            buffers.append([data, int(nbytes)])
            offset += nbytes
        entries[key] = info
    if not snapshot_async:
        d2h.add(offset)
    stall.add((time.perf_counter_ns() - t0) / 1e6)

    manifest = {"format": 1, "process": proc, "nprocs": nproc,
                "step": int(step), "file": f"shard_p{proc}.bin",
                "arrays": entries}
    errbox: list = []
    snap_event = threading.Event() if snapshot_async else None

    def _issue_copies(chunk):
        # enqueue the D2H transfers for one chunk without blocking —
        # chunk k+1's copies run while chunk k's bytes hit the writer
        for slot in chunk:
            start = getattr(slot[0], "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass            # materialize below still copies

    def _finish():
        try:
            w = _open_writer(os.path.join(step_dir, f"shard_p{proc}.bin"))
            if snapshot_async:
                chunks: List[list] = [[]]
                size = 0
                for slot in buffers:
                    if chunks[-1] and size + slot[1] > snapshot_chunk_bytes:
                        chunks.append([])
                        size = 0
                    chunks[-1].append(slot)
                    size += slot[1]
                if chunks[0]:
                    _issue_copies(chunks[0])
                # phase 1 — D2H only: materialize every shard's host
                # copy (chunk-bounded async-copy lookahead). No file
                # I/O here: the wait_snapshot gate must release the
                # moment the last device byte is on the host, not
                # behind serialized writes + CRC of earlier chunks
                # (file writes/fsync/COMMIT are wait()'s job, per the
                # SaveHandle contract). Host RAM is unchanged by the
                # split — np.asarray caches the host copy inside the
                # shard either way.
                hosts: List[np.ndarray] = []
                for ci, chunk in enumerate(chunks):
                    if ci + 1 < len(chunks):
                        _issue_copies(chunks[ci + 1])
                    for slot in chunk:
                        host = np.ascontiguousarray(np.asarray(slot[0]))
                        slot[0] = None
                        hosts.append(host)
                        d2h.add(host.nbytes)
                # every device byte is on the host: training may donate
                # the saved arrays from here on
                snap_event.set()
                # phase 2 — stream to disk in the background of the
                # (now unblocked) training loop
                for hi, host in enumerate(hosts):
                    w.write(host.reshape(-1).view(np.uint8).data)
                    hosts[hi] = None
            else:
                for slot in buffers:
                    # byte view: memoryview can't express bf16, uint8
                    # always works (reshape first — 0-d arrays can't
                    # change dtype)
                    w.write(slot[0].reshape(-1).view(np.uint8).data)
                    slot[0] = None
            total, crc = w.close()
            manifest["file_crc32"] = int(crc)
            manifest["file_bytes"] = int(total)
            _write_json_durable(
                step_dir, f"manifest_p{proc}.json", manifest)
            if meta is not None and proc == 0:
                _write_json_durable(step_dir, "meta.json", meta)
            _fsync_dir(step_dir)
        except BaseException as e:  # surfaced by wait()
            errbox.append(e)
        finally:
            if snap_event is not None:
                snap_event.set()     # error path: never hang the gate

    t = threading.Thread(target=_finish, name=f"ckpt-save-{step}",
                         daemon=False)
    t.start()
    handle = SaveHandle(step_dir, step, t, errbox, snap_event=snap_event)
    if not async_:
        handle.wait()
    return handle


def _write_json_durable(dirname: str, name: str, obj) -> None:
    """write-tmp → fsync → rename: the data blocks are on disk before the
    directory entry appears (COMMIT must never point at partial json)."""
    tmp = os.path.join(dirname, f".{name}.tmp")
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirname, name))


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _sum_across_hosts(value: int) -> int:
    """Sum a small host-local int over all processes (doubles as a
    barrier); single-process returns it unchanged."""
    if jax.process_count() <= 1:
        return int(value)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([value], np.int32))
    return int(np.sum(gathered))


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------


def all_steps(directory: str) -> List[int]:
    """Committed checkpoint steps, ascending."""
    steps = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for n in names:
        if n.startswith("step_") and os.path.exists(
                os.path.join(directory, n, _COMMIT)):
            try:
                steps.append(int(n[len("step_"):]))
            except ValueError:
                pass
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    s = all_steps(directory)
    return s[-1] if s else None


def load_meta(directory: str, step: int) -> Optional[dict]:
    p = os.path.join(directory, _STEP_FMT.format(step), "meta.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


class _ShardSource:
    """All saved shards of one step, indexed by array key."""

    def __init__(self, step_dir: str, verify: bool = False):
        self.step_dir = step_dir
        self.arrays: Dict[str, dict] = {}
        self._files: Dict[str, Any] = {}
        manifests = sorted(n for n in os.listdir(step_dir)
                           if n.startswith("manifest_p"))
        if not manifests:
            raise FileNotFoundError(f"no manifests in {step_dir}")
        for mn in manifests:
            with open(os.path.join(step_dir, mn)) as f:
                m = json.load(f)
            if verify:
                self._verify(m)
            for key, info in m["arrays"].items():
                tgt = self.arrays.setdefault(
                    key, {"shape": info["shape"], "dtype": info["dtype"],
                          "shards": []})
                for sh in info["shards"]:
                    tgt["shards"].append(dict(sh, file=m["file"]))

    def _verify(self, manifest: dict) -> None:
        import zlib

        path = os.path.join(self.step_dir, manifest["file"])
        crc = 0
        with open(path, "rb") as f:
            while True:
                b = f.read(1 << 22)
                if not b:
                    break
                crc = zlib.crc32(b, crc)
        if manifest.get("file_crc32") and crc != manifest["file_crc32"]:
            raise IOError(f"checkpoint corrupt: crc mismatch in {path}")

    def _read(self, fname: str, offset: int, nbytes: int) -> bytes:
        f = self._files.get(fname)
        if f is None:
            f = open(os.path.join(self.step_dir, fname), "rb")
            self._files[fname] = f
        f.seek(offset)
        return f.read(nbytes)

    def close(self):
        for f in self._files.values():
            f.close()
        self._files.clear()

    # -- reading ----------------------------------------------------------
    def exact(self, key: str, index: List[List[int]]) -> Optional[np.ndarray]:
        info = self.arrays[key]
        for sh in info["shards"]:
            if sh["index"] == index:
                shape = [b - a for a, b in index]
                raw = self._read(sh["file"], sh["offset"], sh["nbytes"])
                return np.frombuffer(raw, _np_dtype(info["dtype"])) \
                    .reshape(shape)
        return None

    def assemble(self, key: str) -> np.ndarray:
        info = self.arrays[key]
        out = np.empty(info["shape"], _np_dtype(info["dtype"]))
        covered = 0
        for sh in info["shards"]:
            idx = tuple(slice(a, b) for a, b in sh["index"])
            shape = [b - a for a, b in sh["index"]]
            raw = self._read(sh["file"], sh["offset"], sh["nbytes"])
            out[idx] = np.frombuffer(
                raw, _np_dtype(info["dtype"])).reshape(shape)
            covered += int(np.prod(shape))
        # saved shards are disjoint (replica-0 dedupe), so element count
        # proves coverage; a missing manifest must fail loudly, never
        # hand back uninitialized memory as weights
        total = int(np.prod(info["shape"])) if info["shape"] else 1
        if covered != total:
            raise IOError(
                f"checkpoint incomplete for {key!r}: shards cover "
                f"{covered}/{total} elements (missing per-host manifest?)")
        return out


def restore(directory: str, template, step: Optional[int] = None,
            verify: bool = False):
    """Restore a checkpoint into the shapes/shardings of ``template``.

    template: pytree of jax.Arrays or jax.ShapeDtypeStructs whose
    ``.sharding`` describes the wanted placement. Returns the restored
    pytree (same structure).
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no committed checkpoint in {directory}")
    step_dir = os.path.join(directory, _STEP_FMT.format(step))
    src = _ShardSource(step_dir, verify=verify)
    flat = _flatten(template)
    restored: Dict[str, Any] = {}
    try:
        for key, tgt in flat:
            if tgt is None:
                restored[key] = None
                continue
            if key not in src.arrays:
                raise KeyError(f"checkpoint {step_dir} missing array {key!r}")
            info = src.arrays[key]
            shape = tuple(tgt.shape)
            if list(shape) != list(info["shape"]):
                raise ValueError(
                    f"{key}: checkpoint shape {info['shape']} != template "
                    f"shape {list(shape)}")
            sharding = getattr(tgt, "sharding", None)
            if sharding is None or not hasattr(sharding, "addressable_devices"):
                restored[key] = src.assemble(key).astype(
                    _np_dtype(_dtype_name(tgt.dtype)), copy=False)
                continue
            glob: list = []          # lazy global assembly (shared)

            def cb(index, key=key, info=info, glob=glob):
                norm = _norm_index(index, info["shape"])
                hit = src.exact(key, norm)
                if hit is not None:
                    return hit
                if not glob:
                    glob.append(src.assemble(key))
                return glob[0][tuple(slice(a, b) for a, b in norm)]

            restored[key] = jax.make_array_from_callback(
                shape, sharding, cb)
    finally:
        src.close()
    out_flat = [restored[k] for k, _ in flat]
    leaves, treedef = jax.tree_util.tree_flatten(template)
    return jax.tree_util.tree_unflatten(treedef, out_flat)


def restore_degraded(directory: str, template, verify: bool = True,
                     on_fallback=None, max_step: Optional[int] = None):
    """Degraded-mode restore: newest committed step first, walking back
    to older committed steps when a step turns out unreadable (CRC
    mismatch, truncated or missing shard, lost manifest, mangled JSON)
    instead of raising — a fleet restore must prefer losing a few steps
    of progress over losing the job.

    ``max_step`` caps the walk-back's STARTING point: only committed
    steps ``<= max_step`` are considered. A mesh-agreed rollback uses
    it to pin every rank to the same restore target — the newest commit
    no rank's bad streak had started before — so ranks that committed
    ahead of the streak do not resume from a younger state than the
    proposer (state-lockstep; resilience/runner.py).

    Every skipped step bumps the ``resilience/restore_fallbacks``
    profiler counter and emits a warning; ``on_fallback(step, exc)``
    observes each skip. Returns ``(state, meta, step)``; raises only
    when NO committed step is readable.
    """
    import warnings

    from ..profiler.metrics import registry as _registry

    steps = all_steps(directory)
    if max_step is not None:
        steps = [s for s in steps if s <= max_step]
    if not steps:
        raise FileNotFoundError(
            f"no committed checkpoint in {directory}"
            + (f" at step <= {max_step}" if max_step is not None else ""))
    errors = []
    for step in reversed(steps):
        try:
            state = restore(directory, template, step=step, verify=verify)
            # a step whose META is mangled is as unreadable as one with
            # bad shards — resume needs the rng/cursor in it, so the
            # walk-back must validate (and hand back) the meta here,
            # not die on a second read of it later
            return state, load_meta(directory, step), step
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            errors.append((step, e))
            _registry().counter("resilience/restore_fallbacks").add(1)
            warnings.warn(
                f"checkpoint step {step} unreadable ({e!r}); falling "
                f"back to an older committed step", RuntimeWarning)
            if on_fallback is not None:
                on_fallback(step, e)
    raise IOError(
        f"no readable committed checkpoint in {directory}; tried "
        + ", ".join(f"step {s}: {e!r}" for s, e in errors))


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Rolling async checkpoints with retention.

    ``save`` returns immediately (previous in-flight save is joined
    first); ``restore_latest`` reads the newest committed step.
    """

    def __init__(self, directory: str, keep: int = 3,
                 snapshot_async: bool = False,
                 snapshot_chunk_bytes: int = _SNAPSHOT_CHUNK_BYTES):
        self.directory = directory
        self.keep = keep
        # streamed-snapshot mode (save() docstring): D2H runs chunked on
        # the writer thread; callers with donated state must pass
        # wait_snapshot() before the next step dispatch.
        self.snapshot_async = bool(snapshot_async)
        self.snapshot_chunk_bytes = int(snapshot_chunk_bytes)
        self._pending: Optional[SaveHandle] = None
        os.makedirs(directory, exist_ok=True)

    def save(self, step: int, state, meta: Optional[dict] = None,
             async_: bool = True) -> SaveHandle:
        self.wait()
        h = save(self.directory, state, step, meta=meta, async_=async_,
                 snapshot_async=self.snapshot_async and async_,
                 snapshot_chunk_bytes=self.snapshot_chunk_bytes)
        self._pending = h

        if not async_:
            self._gc()
        return h

    def wait_snapshot(self) -> None:
        """Gate: block until any in-flight save's device→host snapshot
        is complete (no-op otherwise). MUST be passed before dispatching
        a step that donates the saved state."""
        if self._pending is not None:
            self._pending.wait_snapshot()

    def wait(self) -> None:
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
            self._gc()

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def restore(self, template, step: Optional[int] = None,
                verify: bool = False):
        return restore(self.directory, template, step=step, verify=verify)

    def restore_latest(self, template, verify: bool = False):
        step = self.latest_step()
        if step is None:
            return None, None
        state = self.restore(template, step=step, verify=verify)
        return state, load_meta(self.directory, step)

    def restore_degraded(self, template, verify: bool = True,
                         on_fallback=None,
                         max_step: Optional[int] = None):
        """Newest READABLE committed step (walk-back on corruption),
        optionally capped at ``max_step`` (mesh-agreed rollback target);
        returns ``(state, meta, step)`` or ``(None, None, None)`` when
        the directory holds no committed step at all (or none under the
        cap)."""
        try:
            return restore_degraded(self.directory, template,
                                    verify=verify,
                                    on_fallback=on_fallback,
                                    max_step=max_step)
        except FileNotFoundError:
            return None, None, None

    def _gc(self) -> None:
        if jax.process_index() != 0:
            return
        steps = all_steps(self.directory)
        for s in steps[:-self.keep] if self.keep else []:
            shutil.rmtree(
                os.path.join(self.directory, _STEP_FMT.format(s)),
                ignore_errors=True)
        # uncommitted debris older than the newest committed step
        for n in os.listdir(self.directory):
            if not n.startswith("step_"):
                continue
            p = os.path.join(self.directory, n)
            if os.path.exists(os.path.join(p, _COMMIT)):
                continue
            try:
                s = int(n[len("step_"):])
            except ValueError:
                continue
            if steps and s < steps[-1]:
                shutil.rmtree(p, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.wait()
