"""Cursor-accurate background input prefetch for the training loop.

``reader.buffered`` gives the generator-combinator form of this idea (a
fill thread ahead of a consumer); the training loop needs a stronger
contract the queue shape can't express:

  - **cursor accuracy**: the loop consumes batches by DATA CURSOR (a
    resilience rollback re-seeds the cursor past poisoned batches, so
    "next item" is not "cursor + 1"). ``get(cursor)`` hands back the
    staged batch for exactly that cursor; a mismatch — the rollback
    moved the cursor while batches were in flight — discards every
    in-flight batch and restarts the producer at the requested cursor.
  - **blocklist honoring**: ``skip_fn`` (the resilient runner's
    persisted ``skipped_cursors`` set) is consulted BEFORE a cursor is
    fetched or staged, so a poisoned batch is never even read again.
  - **H2D overlap**: the producer runs ``fetch(cursor)`` (the data
    pipeline, with whatever retry wrapper the caller composed) AND the
    optional ``stage`` hook (the trainer's ``_stage_batch`` device_put)
    on the background thread, so the next batch's host→device copy
    overlaps the current step's execution — the double-buffered input
    pipeline of the async step design (ISSUE 3 tentpole (2)).

Thread-safety note: ``stage`` issues jax.device_put from the producer
thread. That is safe — device_put of process-local batch data is not a
collective (the rule that keeps collectives on the caller's thread,
``checkpoint.SaveHandle.wait`` docstring, is about collectives, which
batch staging never issues).

The ``elastic/prefetch_depth`` gauge records how many staged batches
were ready at each consume — the live measure of whether the producer
keeps ahead of the step loop.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

__all__ = ["BatchPrefetcher"]


class BatchPrefetcher:
    """Double-buffered, rollback-aware input prefetcher.

    fetch(cursor) -> batch (tuple, or a single array — normalized to a
        tuple); called on the producer thread.
    stage(batch_tuple) -> staged tuple (e.g. the trainer's H2D
        ``_stage_batch``); optional, also on the producer thread.
    depth: max batches staged ahead (the bounded in-flight window).
    skip_fn(cursor) -> bool: blocklist — skipped before fetch/stage.
    """

    def __init__(self, fetch: Callable, stage: Optional[Callable] = None,
                 depth: int = 2, skip_fn: Optional[Callable] = None):
        self._fetch = fetch
        self._stage = stage
        self.depth = max(1, int(depth))
        self._skip_fn = skip_fn
        self._cond = threading.Condition()
        self._queue: deque = deque()     # (cursor, staged_batch | exc)
        self._gen = 0                    # bumped by invalidate()
        self._next_cursor = 0
        self._inflight: Optional[int] = None
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # observability (tests + post-mortems): how many in-flight
        # batches invalidations have discarded over this lifetime
        self.discarded = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, cursor: int) -> "BatchPrefetcher":
        with self._cond:
            self._next_cursor = int(cursor)
        self._thread = threading.Thread(
            target=self._run, name="batch-prefetch", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- producer ----------------------------------------------------------
    def _skip(self, cursor: int) -> int:
        while self._skip_fn is not None and self._skip_fn(cursor):
            cursor += 1
        return cursor

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and len(self._queue) >= self.depth:
                    self._cond.wait()
                if self._stopped:
                    return
                gen = self._gen
                cursor = self._skip(self._next_cursor)
                self._next_cursor = cursor + 1
                self._inflight = cursor
            try:
                batch = self._fetch(cursor)
                if not isinstance(batch, tuple):
                    batch = (batch,)
                item = self._stage(batch) if self._stage is not None \
                    else batch
            except BaseException as e:   # surfaced by get(), never lost
                item = e
            with self._cond:
                self._inflight = None
                # an invalidation raced this fetch: the batch belongs to
                # a discarded timeline — drop it, never hand it out
                if gen == self._gen and not self._stopped:
                    self._queue.append((cursor, item))
                else:
                    self.discarded += 1
                self._cond.notify_all()

    # -- consumer ----------------------------------------------------------
    def _invalidate_locked(self, cursor: int) -> None:
        self.discarded += len(self._queue)
        self._queue.clear()
        self._gen += 1
        self._next_cursor = int(cursor)
        self._cond.notify_all()

    def invalidate(self, cursor: int) -> None:
        """Rollback: discard every in-flight prefetched batch and
        restart the producer at ``cursor`` (the re-seeded data cursor).
        Batches already being fetched are dropped on arrival."""
        with self._cond:
            self._invalidate_locked(cursor)

    def get(self, cursor: int):
        """The staged batch for exactly ``cursor`` (blocks). A head
        mismatch (the cursor moved underneath us) invalidates the
        in-flight window and refetches."""
        from ..profiler import trace as _ptrace
        from ..profiler.metrics import registry as _registry

        with self._cond:
            while True:
                if self._stopped:
                    raise RuntimeError("BatchPrefetcher is stopped")
                if self._queue:
                    head_cursor, item = self._queue[0]
                    if head_cursor != cursor:
                        self._invalidate_locked(cursor)
                        continue
                    if _ptrace.is_enabled():
                        _registry().gauge("elastic/prefetch_depth").set(
                            len(self._queue))
                    self._queue.popleft()
                    self._cond.notify_all()
                    if isinstance(item, BaseException):
                        raise item
                    return item
                # queue empty: is the producer even heading for cursor?
                heading = (self._inflight == cursor
                           or self._next_cursor == cursor)
                if not heading:
                    self._invalidate_locked(cursor)
                self._cond.wait()
