"""Trace-time distributed context.

The strategy compiler / hybrid trainers set this scope while tracing the
model so that layers (e.g. GPTAttention) can dispatch to mesh-aware
implementations (ring attention over 'sp') without threading the mesh
through every ``forward`` signature. The reference threads the analogous
information through per-rank rewritten programs + global collective ring
ids (reference: fleet meta-optimizers inserting c_* ops keyed by ring_id,
meta_optimizers/common.py); here it is a trace-scoped (mesh, axis) pair.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Tuple

from jax.sharding import Mesh

_SP: Optional[Tuple[Mesh, str, bool]] = None


@contextlib.contextmanager
def sequence_parallel_scope(mesh: Mesh, axis_name: str = "sp"):
    """Within this scope, attention layers use ring attention over
    ``axis_name`` (when the axis is larger than 1)."""
    global _SP
    prev = _SP
    _SP = (mesh, axis_name, False) if mesh.shape.get(axis_name, 1) > 1 \
        else None
    try:
        yield
    finally:
        _SP = prev


@contextlib.contextmanager
def manual_sequence_parallel_scope():
    """Marks that the surrounding code is ALREADY manual over the sp axis
    (e.g. inside the pipeline's shard_map, distributed/pipeline.py) — the
    attention layer then calls the inside-shard_map ring directly instead
    of opening a nested shard_map over the same axis."""
    global _SP
    prev = _SP
    if prev is not None:
        _SP = (prev[0], prev[1], True)
    try:
        yield
    finally:
        _SP = prev


def current_sequence_parallel() -> Optional[Tuple[Mesh, str, bool]]:
    return _SP
