"""Trace-time distributed context.

The strategy compiler / hybrid trainers set this scope while tracing the
model so that layers (e.g. GPTAttention) can dispatch to mesh-aware
implementations (ring attention over 'sp') without threading the mesh
through every ``forward`` signature. The reference threads the analogous
information through per-rank rewritten programs + global collective ring
ids (reference: fleet meta-optimizers inserting c_* ops keyed by ring_id,
meta_optimizers/common.py); here it is a trace-scoped (mesh, axis) pair.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Tuple

from jax.sharding import Mesh

_SP: Optional[Tuple[Mesh, str, bool]] = None


@contextlib.contextmanager
def sequence_parallel_scope(mesh: Mesh, axis_name: str = "sp"):
    """Within this scope, attention layers use ring attention over
    ``axis_name`` (when the axis is larger than 1)."""
    global _SP
    prev = _SP
    _SP = (mesh, axis_name, False) if mesh.shape.get(axis_name, 1) > 1 \
        else None
    try:
        yield
    finally:
        _SP = prev


@contextlib.contextmanager
def manual_sequence_parallel_scope():
    """Marks that the surrounding code is ALREADY manual over the sp axis
    (e.g. inside the pipeline's shard_map, distributed/pipeline.py) — the
    attention layer then calls the inside-shard_map ring directly instead
    of opening a nested shard_map over the same axis."""
    global _SP
    prev = _SP
    if prev is not None:
        _SP = (prev[0], prev[1], True)
    try:
        yield
    finally:
        _SP = prev


def current_sequence_parallel() -> Optional[Tuple[Mesh, str, bool]]:
    return _SP


_PIPE_AUTO: Optional[Tuple[Mesh, Tuple[str, ...]]] = None


@contextlib.contextmanager
def pipeline_auto_axes_scope(mesh: Mesh, axes):
    """Inside the pipeline's shard_map (manual over 'pp'), the remaining
    mesh axes are GSPMD-auto. Mosaic (pallas) kernels cannot be
    auto-partitioned in a *partially* manual region — XLA requires every
    mesh axis manual around a Mosaic call — so kernels consult this scope
    and open a nested shard_map over the listed axes (flash_attention.py).
    CPU meshes never need it (interpret mode is plain HLO)."""
    global _PIPE_AUTO
    prev = _PIPE_AUTO
    _PIPE_AUTO = (mesh, tuple(axes))
    try:
        yield
    finally:
        _PIPE_AUTO = prev


def current_pipeline_auto_axes() -> Optional[Tuple[Mesh, Tuple[str, ...]]]:
    return _PIPE_AUTO


def in_partial_manual_region() -> bool:
    """True when tracing inside a partially-manual region on a real
    (non-interpret) target — the condition under which a Mosaic kernel
    must be nested or avoided. One copy, consulted by both
    flash_attention and ring_attention."""
    from ..core.place import target_platform

    return _PIPE_AUTO is not None and target_platform() != "cpu"


def nested_kernel_shard(fn, in_specs, out_specs):
    """Single shared implementation of the "make every axis manual around
    a Mosaic kernel" rule (used by flash_attention and ring_attention —
    one copy so the mesh-selection logic cannot drift): wraps ``fn`` in a
    shard_map over the scope's remaining auto axes. Returns None when no
    scope is active (fully-auto region — GSPMD handles the kernel
    directly). Inside the pipeline's shard_map the context mesh is the
    AbstractMesh with 'pp' already Manual — shard_map must receive that
    mesh; fall back to the recorded concrete mesh otherwise."""
    pa = current_pipeline_auto_axes()
    if pa is None:
        return None
    mesh, axes = pa

    try:
        from jax.sharding import get_abstract_mesh

        am = get_abstract_mesh()
        use = am if (am is not None and getattr(am, "axis_names", ())) \
            else mesh
    except Exception:
        use = mesh
    from ._compat import shard_map

    return shard_map(fn, mesh=use, in_specs=in_specs,
                     out_specs=out_specs, axis_names=frozenset(axes),
                     check_vma=False)
