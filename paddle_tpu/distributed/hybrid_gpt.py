"""GPT hybrid-parallel trainer: dp × tp × pp × ZeRO in ONE pjit program.

This is the TPU-native composition the reference achieved with a chain of
meta-optimizers rewriting programs per rank (reference:
sharding_optimizer.py + pipeline_optimizer.py + amp/recompute optimizers,
chained by strategy_compiler.py) — here it's sharding specs + shard_map:

  - embeddings / final-norm / lm-head params: GSPMD (tp/zero specs)
  - transformer blocks: params stacked to [pp, layers_per_stage, ...],
    stage axis shard_map'd over 'pp' (pipeline.py), layers scanned within a
    stage, each block optionally rematerialized (jax.checkpoint ==
    reference RecomputeOptimizer),
  - batch sharded over 'dp'; XLA derives gradient reduce-scatter from the
    ZeRO opt-state shardings,
  - bf16 compute / fp32 master params when strategy.amp.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..models.gpt import GPT
from ..static.functional import _swapped_state, state_tensors
from .fleet.distributed_strategy import DistributedStrategy
from .pipeline import pipeline_apply
from .strategy_compiler import (_add_axis, _local_check_shape,
                                build_mesh_from_strategy,
                                resolve_param_specs)


class GPTHybridTrainer:
    def __init__(self, model: GPT, optimizer,
                 strategy: Optional[DistributedStrategy] = None,
                 mesh: Optional[Mesh] = None, n_micro: Optional[int] = None):
        self.model = model
        self.optimizer = optimizer
        self.strategy = strategy or DistributedStrategy()
        self.mesh = mesh if mesh is not None else \
            build_mesh_from_strategy(self.strategy)
        self.pp = self.mesh.shape.get("pp", 1)
        self.n_micro = n_micro or max(
            self.strategy.pipeline_configs.accumulate_steps,
            self.strategy.pipeline_configs.micro_batch, self.pp)
        self.amp = self.strategy.amp
        self.remat = self.strategy.recompute
        self.zero = self.strategy.sharding_configs.sharding_stage \
            if self.strategy.sharding else 0

        L = model.config.num_layers
        if L % self.pp != 0:
            raise ValueError(
                f"num_layers={L} must be divisible by pp_degree={self.pp}")
        self.lps = L // self.pp

        # --- split state: block params (stacked) vs the rest --------------
        pn, pt, bn, bt = state_tensors(model)
        self.all_names = pn
        base_specs = resolve_param_specs(model, self.mesh, zero_stage=0)

        blk0 = [n for n in pn if n.startswith("blocks.0.")]
        self.block_suffixes = [n[len("blocks.0."):] for n in blk0]
        self.other_names = [n for n in pn if not n.startswith("blocks.")]
        name2t = dict(zip(pn, pt))
        self._name2tensor = name2t

        dp = self.mesh.shape.get("dp", 1)

        # stacked block params: [pp, lps, ...]
        self.block_vals: Dict[str, jax.Array] = {}
        self.block_specs: Dict[str, P] = {}
        for sfx in self.block_suffixes:
            per_layer = [name2t[f"blocks.{i}.{sfx}"]._value
                         for i in range(L)]
            stacked = jnp.stack(per_layer, 0).reshape(
                (self.pp, self.lps) + per_layer[0].shape)
            spec0 = base_specs[f"blocks.0.{sfx}"]
            spec = P("pp", None, *spec0)
            if self.zero >= 3:
                shape = _local_check_shape(stacked.shape, spec, self.mesh)
                spec = _add_axis(spec, stacked.ndim, shape, "dp", dp)
            self.block_specs[sfx] = spec
            self.block_vals[sfx] = jax.device_put(
                stacked, NamedSharding(self.mesh, spec))

        self.other_vals: List[jax.Array] = []
        self.other_specs: List[P] = []
        for n in self.other_names:
            spec = base_specs[n]
            t = name2t[n]
            if self.zero >= 3:
                shape = _local_check_shape(t._value.shape, spec, self.mesh)
                spec = _add_axis(spec, t._value.ndim, shape, "dp", dp)
            self.other_specs.append(spec)
            self.other_vals.append(jax.device_put(
                t._value, NamedSharding(self.mesh, spec)))

        # --- optimizer state ----------------------------------------------
        def opt_state_spec(spec, shape, ndim):
            if self.zero >= 1:
                local = _local_check_shape(shape, spec, self.mesh)
                return _add_axis(spec, ndim, local, "dp", dp)
            return spec

        class _FakeParam:
            def __init__(self, v):
                self._value = v

        self.block_opt: Dict[str, dict] = {}
        self.block_opt_specs: Dict[str, dict] = {}
        for sfx, v in self.block_vals.items():
            s = optimizer._init_state(_FakeParam(v))
            sp = opt_state_spec(self.block_specs[sfx], v.shape, v.ndim)
            self.block_opt[sfx] = jax.device_put(
                s, {k: NamedSharding(self.mesh, sp) for k in s})
            self.block_opt_specs[sfx] = {k: sp for k in s}
        self.other_opt: List[dict] = []
        self.other_opt_specs: List[dict] = []
        for n, v, spec in zip(self.other_names, self.other_vals,
                              self.other_specs):
            s = optimizer._init_state(_FakeParam(v))
            sp = opt_state_spec(spec, v.shape, v.ndim)
            self.other_opt.append(jax.device_put(
                s, {k: NamedSharding(self.mesh, sp) for k in s}))
            self.other_opt_specs.append({k: sp for k in s})

        self._step = 0
        self._build()

    # ---------------------------------------------------------------------
    def _forward_loss(self, block_params, other_params, tokens, key):
        model = self.model
        cfg = model.config
        from ..core import rng as rng_mod

        if self.amp:
            castf = lambda v: v.astype(jnp.bfloat16) if \
                jnp.issubdtype(v.dtype, jnp.floating) else v
        else:
            castf = lambda v: v
        other_cast = [castf(v) for v in other_params]
        block_cast = {k: castf(v) for k, v in block_params.items()}

        other_tensors = [self._name2tensor[n] for n in self.other_names]
        blk0_tensors = [self._name2tensor[f"blocks.0.{s}"]
                        for s in self.block_suffixes]
        sp = self.mesh.shape.get("sp", 1)

        def seq_constraint(h):
            """Keep activations sequence-sharded between ring attentions.
            Skipped for bf16 on XLA:CPU (tests): resharding constraints on
            bf16 trip a CPU-backend crash; TPU is unaffected."""
            if sp > 1 and not (jax.default_backend() == "cpu"
                               and h.dtype == jnp.bfloat16):
                return jax.lax.with_sharding_constraint(
                    h, NamedSharding(self.mesh, P("dp", "sp", None)))
            return h

        from . import context as dctx
        manual_sp = sp > 1 and self.pp > 1

        def block_apply(stage_local, x):
            """Apply one stage's lps blocks (lax.scan over layers)."""
            def one_block(h, layer_params):
                vals = [layer_params[s] for s in self.block_suffixes]
                with _swapped_state(blk0_tensors, vals):
                    if manual_sp:
                        # pipeline shard_map is manual over sp too:
                        # attention runs the in-context ring directly
                        with dctx.manual_sequence_parallel_scope():
                            out = model.blocks[0](Tensor(h))._value
                    else:
                        out = model.blocks[0](Tensor(h))._value
                return out

            if self.remat:
                one_block = jax.checkpoint(one_block)

            def body(h, layer_params):
                return one_block(h, layer_params), None

            # unrolling the layer loop on TPU removes the scan's
            # dynamic-update-slice residual bookkeeping (~11% step time at
            # GPT-125M); CPU (tests) keeps the rolled scan for compile time
            out, _ = jax.lax.scan(body, x, stage_local,
                                  unroll=jax.default_backend() != "cpu")
            return out

        with _swapped_state(other_tensors, other_cast), \
                dctx.sequence_parallel_scope(self.mesh):
            with rng_mod.key_scope(key):
                x = model.embeddings(Tensor(tokens))._value
                x = seq_constraint(x)
                x = pipeline_apply(self.mesh, block_apply, block_cast, x,
                                   self.n_micro,
                                   sp_axis="sp" if manual_sp else None)
                x = Tensor(seq_constraint(x))
                x = model.ln_f(x)
                # fused lm-head + CE: logits never hit HBM (ops/fused_ce.py).
                # Chunking over seq would fight an sp sharding, so sp>1 runs
                # one chunk (GSPMD already divides the logits tile by sp).
                from ..ops.fused_ce import (fused_linear_cross_entropy_fn,
                                            shifted_labels)

                labels = shifted_labels(tokens)
                ck = None if sp > 1 else 256
                if cfg.tie_word_embeddings:
                    w = model.embeddings.wte.weight._value       # [V, H]
                    loss = fused_linear_cross_entropy_fn(
                        x._value, w, labels, chunk=ck)
                else:
                    w = model.lm_head.weight._value              # [H, V]
                    loss = fused_linear_cross_entropy_fn(
                        x._value, w, labels, chunk=ck, transpose_w=True)
        return loss.astype(jnp.float32)

    def _build(self):
        from .strategy_compiler import functional_clip, make_param_update

        opt = self.optimizer
        clip = opt._grad_clip
        mesh = self.mesh
        wd_other = tuple(opt._decoupled_wd(self._name2tensor[n])
                         for n in self.other_names)
        lr_other = tuple(
            self._name2tensor[n].optimize_attr.get("learning_rate", 1.0)
            for n in self.other_names)
        wd_block = {s: opt._decoupled_wd(
            self._name2tensor[f"blocks.0.{s}"])
            for s in self.block_suffixes}
        lr_block = {s: self._name2tensor[
            f"blocks.0.{s}"].optimize_attr.get("learning_rate", 1.0)
            for s in self.block_suffixes}
        upd = make_param_update(opt)

        def step_fn(block_params, other_params, block_opt, other_opt,
                    tokens, lr, step_no, key):
            def loss_of(bp, op):
                return self._forward_loss(bp, op, tokens, key)

            loss, (g_blk, g_oth) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(block_params, other_params)
            g_blk, g_oth = functional_clip(clip, (g_blk, g_oth))

            new_blk, new_blk_opt = {}, {}
            for sfx in block_params:
                np_, ns = upd(block_params[sfx], g_blk[sfx],
                              block_opt[sfx], lr, step_no,
                              plr=lr_block[sfx], wd=wd_block[sfx])
                new_blk[sfx] = np_
                new_blk_opt[sfx] = ns
            new_oth, new_oth_opt = [], []
            for p, g, s, plr, wd in zip(other_params, g_oth, other_opt,
                                        lr_other, wd_other):
                np_, ns = upd(p, g, s, lr, step_no, plr=plr, wd=wd)
                new_oth.append(np_)
                new_oth_opt.append(ns)
            return loss, new_blk, new_oth, new_blk_opt, new_oth_opt

        ns = lambda spec: NamedSharding(mesh, spec)
        blk_sh = {k: ns(v) for k, v in self.block_specs.items()}
        oth_sh = [ns(s) for s in self.other_specs]
        blk_opt_sh = {k: {kk: ns(vv) for kk, vv in v.items()}
                      for k, v in self.block_opt_specs.items()}
        oth_opt_sh = [{kk: ns(vv) for kk, vv in d.items()}
                      for d in self.other_opt_specs]
        tok_spec = P("dp", "sp") if mesh.shape.get("sp", 1) > 1 else P("dp")
        self._token_sharding = ns(tok_spec)
        self._step_fn = jax.jit(
            step_fn,
            in_shardings=(blk_sh, oth_sh, blk_opt_sh, oth_opt_sh,
                          self._token_sharding, None, None, None),
            out_shardings=(ns(P()), blk_sh, oth_sh, blk_opt_sh, oth_opt_sh),
            donate_argnums=(0, 1, 2, 3))

    def step(self, tokens) -> jax.Array:
        from ..core import rng as rng_mod

        self._step += 1
        v = tokens._value if isinstance(tokens, Tensor) else \
            jnp.asarray(tokens)
        v = jax.device_put(v, self._token_sharding)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, self.block_vals, self.other_vals, self.block_opt, \
            self.other_opt = self._step_fn(
                self.block_vals, self.other_vals, self.block_opt,
                self.other_opt, v, lr, jnp.asarray(self._step, jnp.int32),
                rng_mod.next_key())
        self.optimizer._global_step = self._step
        return loss

    __call__ = step

    # -- sharded checkpoint integration (distributed/checkpoint.py) -------
    def device_state(self):
        """The trainer's on-device state as one pytree of sharded arrays
        (params + optimizer state), for distributed.checkpoint.save."""
        return {"block": dict(self.block_vals),
                "other": list(self.other_vals),
                "block_opt": {k: dict(v) for k, v in self.block_opt.items()},
                "other_opt": [dict(d) for d in self.other_opt]}

    def load_device_state(self, st, step: Optional[int] = None):
        """Inverse of device_state (resume-exact: same values, shardings)."""
        self.block_vals = dict(st["block"])
        self.other_vals = list(st["other"])
        self.block_opt = {k: dict(v) for k, v in st["block_opt"].items()}
        self.other_opt = [dict(d) for d in st["other_opt"]]
        if step is not None:
            self._step = int(step)
            self.optimizer._global_step = int(step)

    def sync_to_layer(self):
        """Unstack device state (params AND optimizer accumulators) back
        into the eager model/optimizer, so state_dict/checkpoints see the
        trained values."""
        L = self.model.config.num_layers
        for sfx, stacked in self.block_vals.items():
            flat = stacked.reshape((L,) + tuple(stacked.shape[2:]))
            opt_flat = {k: v.reshape((L,) + tuple(v.shape[2:]))
                        for k, v in self.block_opt[sfx].items()}
            for i in range(L):
                t = self._name2tensor[f"blocks.{i}.{sfx}"]
                t._value = flat[i]
                self.optimizer._accumulators[id(t)] = {
                    k: v[i] for k, v in opt_flat.items()}
        for n, v, s in zip(self.other_names, self.other_vals,
                           self.other_opt):
            t = self._name2tensor[n]
            t._value = v
            self.optimizer._accumulators[id(t)] = s
        return self.model
