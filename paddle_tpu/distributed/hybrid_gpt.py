"""GPT hybrid-parallel trainer — back-compat name for the generic
HybridPipelineTrainer (distributed/hybrid.py).

Round-1 shipped this trainer hardwired to the GPT block layout (the
"blocks.0." name contract); the generalization moved the machinery into
distributed/hybrid.py behind the pipeline protocol
(pipeline_stem/pipeline_blocks/pipeline_head, declared by models/gpt.py,
models/bert.py). This module keeps the public name and the GPT-specific
docstrings' reference citations alive: the reference achieved the same
composition with per-rank program rewriting chained by
fleet/base/strategy_compiler.py (sharding_optimizer.py +
pipeline_optimizer.py + amp/recompute meta-optimizers).
"""
from __future__ import annotations

from .hybrid import HybridPipelineTrainer


class GPTHybridTrainer(HybridPipelineTrainer):
    """``HybridPipelineTrainer`` under its round-1 name; ``step(tokens)``."""
