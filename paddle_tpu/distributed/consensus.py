"""Cross-host agreement: a deterministic all-gather vote with
epoch/lease semantics over the process mesh (ISSUE 13 tentpole piece 2).

Everything multi-host in this repo needs the SAME small primitive, so
it is built once here and used twice:

- **serving admission / handoff routing** (serving/disagg.py): the
  ranks of a serving mesh vote their load each admission round; the
  agreed decision assigns every pending request to exactly one rank,
  deterministically, so no two hosts ever admit the same request;
- **resilience rollback/abort** (resilience/runner.py): ONE rank's
  K-consecutive-bad verdict becomes a mesh-wide agreed rollback (or
  abort) instead of per-rank divergence — the cross-host agreement the
  resilience layer has listed as residue since PR 2.

Why a shared-directory board and not a jax collective: an agreement
protocol must reach a decision precisely when the mesh is UNHEALTHY —
a dead or hung peer is the input, not an error. Compiled collectives
hang (by design) when a participant dies, and this container's jax
0.4.37 cannot run multiprocess computations on the CPU backend at all,
so the control plane rides the same substrate the checkpoint/resume
machinery already trusts: a shared filesystem. (On a real TPU fleet the
board directory is the job's existing shared checkpoint/artifact store;
the data plane — grads, KV pages — stays on ICI.) The jax coordination
service still does process bring-up (tools/mp_mesh.py) — the board does
membership and votes, where liveness timeouts are required semantics.

Protocol (per topic *family*, e.g. ``"admit"`` or ``"rollback"``):

- every rank keeps a **lease** alive (``lease.<rank>`` mtime,
  refreshed by ``heartbeat()``; every vote/poll refreshes it). A rank
  whose lease is older than ``lease_s`` is *suspect* — votes are no
  longer awaited from it.
- decisions happen in dense **epochs** 0, 1, 2, ... per family. Each
  rank casts at most one immutable vote per epoch
  (``<family>/e<epoch>/vote.<rank>``).
- the **leader** — the lowest-ranked live rank — publishes the
  decision once every live rank has voted, or once the epoch's vote
  window (``window_s``, anchored at the epoch's first vote) expires
  with at least one vote. Publication is an atomic exclusive link of an
  immutable ``decision.json``; if two ranks race to lead (lease flap),
  exactly one file wins and the loser adopts it. Leader death hands
  leadership to the next live rank by lease expiry — no election
  round.
- every rank — voter or not, live or late — adopts the decision by
  reading that one immutable file, then advances its epoch cursor.
  A rank that slept through epochs catches up by reading the dense
  decision history in order; this is what makes the vote an
  *all-gather*: the decision carries every vote it was reduced from.

The decision VALUE is computed by the leader from the votes (sorted by
rank — deterministic) with the caller's reducer; followers take the
published value, so agreement never depends on every rank re-deriving
it. Reducers: ``any``/``all`` (bools), ``majority`` (most common
value, lowest-rank tie-break), ``min``/``max``, ``union`` (sorted
union of list votes), ``first`` (lowest-ranked vote), or a callable
``f(votes: {rank: value}) -> value`` (must be the same on all ranks).

Single-process meshes (world == 1) decide immediately and touch the
disk only for the decision record, so the primitive costs nothing to
leave wired in single-host code paths.

Observability (ISSUE 14): every adoption increments
``consensus/epochs_adopted``, measures the vote round trip
(``consensus/vote_rtt_ms`` histogram — cast to adopted, when this rank
voted) and emits a ``consensus_decision`` event; lease expiries
(``consensus/lease_expiries`` + ``lease_expiry`` events) and
vote-window expiries (``consensus/vote_window_expiries`` +
``vote_window_expiry`` events, naming the ranks published-without) are
counted at the transition — all flushed through the normal metrics
sink, all shielded so telemetry can never break agreement.
:func:`adopted_epochs` is the process-global {family: last epoch} the
flight recorder stamps into post-mortem dumps.

Honest limits: liveness is mtime-based, so multi-NODE boards need a
shared filesystem with coherent timestamps (the CPU test mesh runs on
one node; a real fleet would back the board with its coordination
service's KV store — the transport is three small functions). A rank
that dies AFTER voting still counts: its vote is a fact on the board.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from collections import Counter as _Counter
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = ["Consensus", "Decision", "ConsensusTimeout", "REDUCERS",
           "adopted_epochs", "lease_ages"]

#: adopted epochs kept on disk behind every live rank's cursor — the
#: replay window a transiently-slow rank can still read; everything
#: older is pruned (a long-lived mesh must not leak one directory per
#: agreement round forever)
KEEP_EPOCHS = 8


class ConsensusTimeout(RuntimeError):
    """decide() ran out of time before a decision was published."""


#: last adopted epoch per family, process-global (ISSUE 14): the
#: flight recorder stamps this into post-mortem dumps so dumps from
#: different ranks can be ordered by agreement history, not just wall
#: clocks. Written on every adoption; a process driving several
#: Consensus instances (in-process mesh tests) sees the newest.
_ADOPTED: Dict[str, int] = {}
_ADOPTED_LOCK = threading.Lock()


def adopted_epochs() -> Dict[str, int]:
    """{family: last adopted epoch} for this process."""
    with _ADOPTED_LOCK:
        return dict(_ADOPTED)


def lease_ages(board_dir: str,
               world: Optional[int] = None) -> Dict[int, float]:
    """{rank: seconds since its ``lease.<rank>`` was refreshed} read
    straight off the board directory — a pure-stdlib OBSERVER's view
    of mesh liveness (ISSUE 16: the LiveAggregator corroborates frame
    staleness with this before flagging a rank dead; it runs on the
    driver and holds no Consensus instance). A rank with NO lease file
    is simply absent from the result — never fabricated. ``world``
    bounds the scan when given; otherwise every ``lease.*`` file on
    the board is reported."""
    out: Dict[int, float] = {}
    now = time.time()
    try:
        names = os.listdir(board_dir)
    except OSError:
        return out
    for n in names:
        if not n.startswith("lease."):
            continue
        try:
            r = int(n[len("lease."):])
        except ValueError:
            continue
        if world is not None and not 0 <= r < world:
            continue
        try:
            out[r] = max(0.0, now - os.path.getmtime(
                os.path.join(board_dir, n)))
        except OSError:
            pass
    return out


class Decision:
    """One published, immutable agreement.

    epoch:        dense per-family decision index.
    value:        the reduced (agreed) value — what callers act on.
    votes:        {rank: vote} actually received (sorted by rank).
    participants: ranks whose votes were reduced.
    missing:      ranks alive at epoch start that never voted inside
                  the window, plus suspects — the fault evidence.
    leader:       rank that published.
    """

    __slots__ = ("family", "epoch", "value", "votes", "participants",
                 "missing", "leader")

    def __init__(self, family: str, epoch: int, value, votes: Dict[int, Any],
                 participants: List[int], missing: List[int], leader: int):
        self.family = family
        self.epoch = epoch
        self.value = value
        self.votes = votes
        self.participants = participants
        self.missing = missing
        self.leader = leader

    def to_dict(self) -> dict:
        return {"family": self.family, "epoch": self.epoch,
                "value": self.value,
                "votes": {str(r): v for r, v in self.votes.items()},
                "participants": self.participants,
                "missing": self.missing, "leader": self.leader}

    @classmethod
    def from_dict(cls, d: dict) -> "Decision":
        return cls(d["family"], int(d["epoch"]), d["value"],
                   {int(r): v for r, v in d["votes"].items()},
                   [int(r) for r in d["participants"]],
                   [int(r) for r in d["missing"]], int(d["leader"]))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Decision({self.family}#{self.epoch} -> {self.value!r} "
                f"votes={self.votes!r} missing={self.missing!r})")


def _majority(votes: Dict[int, Any]):
    """Most common vote value; ties break toward the value held by the
    lowest-ranked voter (deterministic without value ordering)."""
    counts = _Counter(json.dumps(v, sort_keys=True)
                      for v in votes.values())
    best = max(counts.values())
    for r in sorted(votes):
        if counts[json.dumps(votes[r], sort_keys=True)] == best:
            return votes[r]
    raise ValueError("majority of zero votes")  # pragma: no cover


REDUCERS: Dict[str, Callable[[Dict[int, Any]], Any]] = {
    "any": lambda v: any(bool(x) for x in v.values()),
    "all": lambda v: all(bool(x) for x in v.values()),
    "majority": _majority,
    "min": lambda v: min(v[r] for r in sorted(v)),
    "max": lambda v: max(v[r] for r in sorted(v)),
    "union": lambda v: sorted({x for vv in v.values() for x in vv}),
    "first": lambda v: v[min(v)],
}


class Consensus:
    """See module docstring. One instance per rank per board."""

    def __init__(self, board_dir: str, rank: int, world: int, *,
                 lease_s: float = 5.0, window_s: Optional[float] = None,
                 poll_s: float = 0.02, timeout_s: float = 60.0):
        if world < 1 or not 0 <= rank < world:
            raise ValueError(f"bad rank/world {rank}/{world}")
        if lease_s <= 0:
            raise ValueError("lease_s must be > 0")
        self.dir = board_dir
        self.rank = int(rank)
        self.world = int(world)
        self.lease_s = float(lease_s)
        #: a live-but-slow rank gets this long from the epoch's FIRST
        #: vote before the leader decides without it (a dead rank is
        #: dropped sooner, at lease expiry)
        self.window_s = float(window_s) if window_s is not None \
            else 4.0 * float(lease_s)
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self._epochs: Dict[str, int] = {}
        #: when THIS rank voted in (family, epoch) — the anchor of the
        #: vote round-trip measurement (vote cast -> decision adopted)
        self._vote_t: Dict[tuple, float] = {}
        #: previously-observed live set; None until the first alive()
        #: call so mesh bring-up (peers' leases not written yet) does
        #: not read as a storm of expiries
        self._last_alive: Optional[set] = None
        self._hb_stop: Optional[threading.Event] = None
        self._hb_thread: Optional[threading.Thread] = None
        os.makedirs(board_dir, exist_ok=True)
        self.heartbeat()

    @classmethod
    def for_mesh(cls, board_dir: str, **kw) -> "Consensus":
        """Build from the ambient jax process mesh (rank 0 of 1 when
        jax.distributed was never initialized). Uses the ONE guarded
        rank/world detection helper (profiler.sink), which avoids
        forcing backend bring-up as a side effect."""
        from ..profiler.sink import _detect_rank, _detect_world

        return cls(board_dir, _detect_rank(), _detect_world(), **kw)

    # -- leases ------------------------------------------------------------
    def _lease_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"lease.{rank}")

    def heartbeat(self) -> None:
        """Refresh this rank's lease. Called implicitly by every vote
        and poll; loops that can stall (compile, checkpoint I/O) should
        call it at their own boundaries."""
        p = self._lease_path(self.rank)
        try:
            os.utime(p)
        except OSError:
            with open(p, "w") as f:
                f.write(str(os.getpid()))

    def start_heartbeat(self, interval_s: Optional[float] = None
                        ) -> "Consensus":
        """Refresh the lease from a daemon thread (default every
        ``lease_s / 3``). Use whenever the calling loop can stall
        longer than the lease — a rank COMPILING its first program for
        a minute is alive, and its lease must say so. A killed process
        stops heartbeating (threads die with it), which is exactly the
        signal the board wants; a HUNG process keeps its lease — that
        is the vote window's job, not the lease's."""
        if self._hb_thread is not None:
            return self
        beat = max((self.lease_s / 3.0) if interval_s is None
                   else float(interval_s), 0.02)
        self._hb_stop = threading.Event()

        def loop():
            while not self._hb_stop.wait(beat):
                try:
                    self.heartbeat()
                except OSError:  # pragma: no cover - board removed
                    pass

        self._hb_thread = threading.Thread(
            target=loop, name="consensus-heartbeat", daemon=True)
        self._hb_thread.start()
        return self

    def stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=2)
        self._hb_thread = None
        self._hb_stop = None

    def board_ranks(self) -> List[int]:
        """Every rank with a ``lease.<r>`` file on the board — the
        DISCOVERED membership candidates (ISSUE 17: a joiner outside
        this instance's original ``world`` announces itself by writing
        its lease; static meshes see exactly ``range(world)`` because
        nobody else ever writes one). Self always counts."""
        cand = set(range(self.world))
        cand.add(self.rank)
        try:
            names = os.listdir(self.dir)
        except OSError:
            return sorted(cand)
        for n in names:
            if n.startswith("lease.") and ".tmp" not in n:
                try:
                    cand.add(int(n[len("lease."):]))
                except ValueError:
                    pass
        return sorted(cand)

    def alive(self) -> List[int]:
        """Ranks with a fresh lease (self always counts). Candidates
        are discovered from the board (:meth:`board_ranks`), not
        assumed from ``world`` — leadership and vote-await semantics
        follow the mesh that actually exists, so a mid-run joiner is
        awaited the moment its lease lands (ISSUE 17)."""
        now = time.time()
        out = []
        for r in self.board_ranks():
            if r == self.rank:
                out.append(r)
                continue
            try:
                if now - os.path.getmtime(self._lease_path(r)) \
                        < self.lease_s:
                    out.append(r)
            except OSError:
                pass
        cur = set(out)
        if self._last_alive is not None and cur != self._last_alive:
            for r in sorted(self._last_alive - cur):
                _note_lease_expiry(r)
        self._last_alive = cur
        return out

    # -- epochs ------------------------------------------------------------
    def _family_dir(self, family: str) -> str:
        if "/" in family or family.startswith("lease."):
            raise ValueError(f"bad family name {family!r}")
        return os.path.join(self.dir, family)

    def _epoch_dir(self, family: str, epoch: int) -> str:
        return os.path.join(self._family_dir(family), f"e{epoch:06d}")

    def epoch(self, family: str) -> int:
        """This rank's current (next unadopted) epoch for ``family``.
        Always starts at 0: a rank that slept through epochs (or a
        restarted one) adopts the dense published history IN ORDER —
        every decision carries assignments/verdicts the rank must act
        on, so skipping ahead would silently drop agreements."""
        if family not in self._epochs:
            self._epochs[family] = 0
            os.makedirs(self._family_dir(family), exist_ok=True)
        return self._epochs[family]

    def fast_forward(self, family: str) -> int:
        """Joiner catch-up (ISSUE 17): position this rank's epoch
        cursor at the OLDEST epoch still on the board for ``family``.
        A rank that joins after earlier epochs were pruned
        (KEEP_EPOCHS) cannot adopt them in order — ``epoch()``'s dense
        contract would stall it forever at a directory that no longer
        exists. It fast-forwards to the surviving history's head and
        adopts from there; whatever state the pruned epochs carried
        reaches it through the membership decision's sync snapshot
        (serving/disagg.py ``_member_reducer``). Returns the cursor
        (unchanged — possibly 0 — when the full history survives)."""
        fam = self._family_dir(family)
        cur = self.epoch(family)
        oldest: Optional[int] = None
        try:
            names = os.listdir(fam)
        except OSError:
            names = []
        for n in names:
            if n.startswith("e") and len(n) == 7 and n[1:].isdigit():
                e = int(n[1:])
                oldest = e if oldest is None else min(oldest, e)
        if oldest is not None and oldest > cur:
            self._epochs[family] = oldest
        return self._epochs[family]

    # -- voting ------------------------------------------------------------
    def vote(self, family: str, value) -> None:
        """Cast this rank's (immutable, idempotent) vote in the current
        epoch. A second vote in the same epoch is ignored — re-voting a
        DIFFERENT value in one epoch is a caller bug, not a protocol
        feature."""
        self.heartbeat()
        ed = self._epoch_dir(family, self.epoch(family))
        os.makedirs(ed, exist_ok=True)
        path = os.path.join(ed, f"vote.{self.rank}")
        self._vote_t.setdefault((family, self.epoch(family)),
                                time.monotonic())
        if os.path.exists(path):
            return
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "value": value,
                       "t": time.time()}, f)
        try:
            os.link(tmp, path)      # exclusive: first vote wins
        except FileExistsError:
            pass
        finally:
            os.unlink(tmp)

    def pending(self, family: str) -> bool:
        """True when the current epoch already has activity (a vote or
        a decision) — how a healthy rank notices, at its own step
        boundary, that a peer opened a proposal it should join."""
        ed = self._epoch_dir(family, self.epoch(family))
        try:
            return bool(os.listdir(ed))
        except OSError:
            return False

    def _read_votes(self, ed: str) -> Dict[int, Any]:
        votes: Dict[int, Any] = {}
        try:
            names = os.listdir(ed)
        except OSError:
            return votes
        for n in names:
            if not n.startswith("vote.") or ".tmp" in n:
                continue
            try:
                with open(os.path.join(ed, n)) as f:
                    d = json.load(f)
                votes[int(d["rank"])] = d["value"]
            except (OSError, ValueError, KeyError):
                continue            # torn concurrent write: next poll
        return votes

    def _first_vote_t(self, ed: str) -> Optional[float]:
        ts = []
        try:
            names = os.listdir(ed)
        except OSError:
            return None
        for n in names:
            if n.startswith("vote.") and ".tmp" not in n:
                try:
                    ts.append(os.path.getmtime(os.path.join(ed, n)))
                except OSError:
                    pass
        return min(ts) if ts else None

    def outcome(self, family: str,
                reducer: Union[str, Callable] = "majority"
                ) -> Optional[Decision]:
        """Non-blocking: the current epoch's decision if one can be
        adopted or published right now, else None. Adopting a decision
        advances the epoch cursor, so the next vote opens the next
        epoch."""
        self.heartbeat()
        e = self.epoch(family)
        ed = self._epoch_dir(family, e)
        dpath = os.path.join(ed, "decision.json")
        dec = self._try_read_decision(dpath)
        if dec is None:
            snap = self._should_publish(family, ed)
            if snap is not None:
                dec = self._publish(family, e, ed, dpath, reducer,
                                    *snap)
        if dec is not None:
            self._epochs[family] = e + 1
            rtt = self._vote_t.pop((family, e), None)
            _note_adoption(dec, None if rtt is None
                           else (time.monotonic() - rtt) * 1e3)
            self._note_adopted(family, e)
        return dec

    def decide(self, family: str, value, *,
               reducer: Union[str, Callable] = "majority",
               timeout_s: Optional[float] = None) -> Decision:
        """Blocking all-gather vote: cast ``value``, poll until the
        epoch's decision exists (publishing it if this rank becomes the
        leader), adopt it. Raises ConsensusTimeout past ``timeout_s``."""
        self.vote(family, value)
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        while True:
            dec = self.outcome(family, reducer)
            if dec is not None:
                return dec
            if time.monotonic() > deadline:
                raise ConsensusTimeout(
                    f"{family}#{self.epoch(family)}: no decision within "
                    f"timeout (alive={self.alive()})")
            time.sleep(self.poll_s)

    # -- history bounds ----------------------------------------------------
    def _note_adopted(self, family: str, epoch: int) -> None:
        """Publish this rank's adopted-epoch cursor and periodically
        prune history every live rank is past: decisions are immutable
        facts, but an agreement board that grows one directory per
        round forever is a filesystem leak on a long-lived mesh.
        Epochs newer than ``min(live cursors) - KEEP_EPOCHS`` survive
        so a transiently-slow rank still catches up in order; a rank
        dead past its lease that later revives may find its history
        pruned — it was not a member anymore (documented residue)."""
        fam = self._family_dir(family)
        path = os.path.join(fam, f"cursor.{self.rank}")
        tmp = path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(str(epoch))
            os.replace(tmp, path)
        except OSError:  # pragma: no cover - board dir vanished
            return
        if (epoch + 1) % KEEP_EPOCHS != 0:
            return
        cursors = []
        for r in self.alive():
            try:
                with open(os.path.join(fam, f"cursor.{r}")) as f:
                    cursors.append(int(f.read()))
            except (OSError, ValueError):
                return          # a live rank with no cursor: no prune
        cut = min(cursors) - KEEP_EPOCHS + 1
        try:
            names = os.listdir(fam)
        except OSError:  # pragma: no cover
            return
        for n in names:
            if n.startswith("e") and len(n) == 7 and n[1:].isdigit() \
                    and int(n[1:]) < cut:
                shutil.rmtree(os.path.join(fam, n), ignore_errors=True)

    # -- leader path -------------------------------------------------------
    def _try_read_decision(self, dpath: str) -> Optional[Decision]:
        try:
            with open(dpath) as f:
                return Decision.from_dict(json.load(f))
        except OSError:
            return None
        except ValueError:          # pragma: no cover - torn mid-link
            return None             # read (impossible: link is atomic)

    def _should_publish(self, family: str, ed: str):
        """The publish decision AND its evidence: (votes, live) when
        this rank should publish right now, else None. The snapshot is
        handed to _publish verbatim — recomputing liveness there could
        see a lease flap and blame a rank that was never waited out."""
        live = self.alive()
        if self.rank != min(live):
            return None             # not the leader
        votes = self._read_votes(ed)
        if not votes:
            return None             # nothing to decide from
        if all(r in votes for r in live):
            return votes, live      # every live rank voted
        t0 = self._first_vote_t(ed)
        if t0 is not None and time.time() - t0 > self.window_s:
            return votes, live
        return None

    def _publish(self, family: str, epoch: int, ed: str, dpath: str,
                 reducer: Union[str, Callable], votes: Dict[int, Any],
                 live: List[int]) -> Optional[Decision]:
        red = REDUCERS[reducer] if isinstance(reducer, str) else reducer
        missing = sorted(set(range(self.world)) - set(votes))
        waited_out = sorted(set(live) - set(votes))
        if waited_out:
            # publishing WITHOUT every live vote: the epoch's window
            # expired on someone — fault evidence worth an event
            _note_window_expiry(family, epoch, waited_out)
        dec = Decision(family, epoch, red(dict(sorted(votes.items()))),
                       dict(sorted(votes.items())), sorted(votes),
                       missing, self.rank)
        tmp = dpath + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dec.to_dict(), f)
        try:
            os.link(tmp, dpath)     # exclusive publish: one winner
        except FileExistsError:
            dec = self._try_read_decision(dpath)   # adopt the winner's
        finally:
            os.unlink(tmp)
        _note_decision(family, live)
        return dec


def _note_decision(family: str, live: List[int]) -> None:
    """Profiler breadcrumbs — decisions are rare, counters are cheap."""
    try:
        from ..profiler.metrics import registry

        registry().counter(f"consensus/decisions_{family}").add(1)
        registry().gauge("consensus/live_ranks").set(float(len(live)))
    except Exception:               # pragma: no cover - metrics must
        pass                        # never break agreement


def _note_adoption(dec: Decision, rtt_ms: Optional[float]) -> None:
    """ISSUE 14 consensus observability: every adoption counts an
    epoch, times the vote round trip (cast -> adopted, only when this
    rank voted in the epoch) and leaves a ``consensus_decision`` event
    — all flushed through the normal sink, all guarded (telemetry must
    never break agreement)."""
    with _ADOPTED_LOCK:
        _ADOPTED[dec.family] = dec.epoch
    try:
        from ..profiler import events as _events
        from ..profiler.metrics import registry

        registry().counter("consensus/epochs_adopted").add(1)
        attrs = {"family": dec.family, "epoch": dec.epoch,
                 "leader": dec.leader, "missing": len(dec.missing)}
        if rtt_ms is not None:
            registry().histogram("consensus/vote_rtt_ms").observe(rtt_ms)
            attrs["rtt_ms"] = round(rtt_ms, 3)
        _events.emit("consensus_decision", **attrs)
    except Exception:               # pragma: no cover
        pass


def _note_lease_expiry(peer: int) -> None:
    try:
        from ..profiler import events as _events
        from ..profiler.metrics import registry

        registry().counter("consensus/lease_expiries").add(1)
        _events.emit("lease_expiry", peer=int(peer))
    except Exception:               # pragma: no cover
        pass


def _note_window_expiry(family: str, epoch: int,
                        waiting_on: List[int]) -> None:
    try:
        from ..profiler import events as _events
        from ..profiler.metrics import registry

        registry().counter("consensus/vote_window_expiries").add(1)
        _events.emit("vote_window_expiry", family=family,
                     epoch=int(epoch),
                     waiting_on=[int(r) for r in waiting_on])
    except Exception:               # pragma: no cover
        pass
