"""Strategy compiler: DistributedStrategy → ONE pjit'd SPMD train step.

TPU-native replacement for the reference's meta-optimizer chain
(reference: fleet/base/strategy_compiler.py:1-211 + meta_optimizers/* —
which rewrite per-rank ProgramDescs, insert c_broadcast/c_allreduce ops,
prune non-owned optimizer ops, etc.). Here the same user intent — dp/tp/pp
degrees, ZeRO stage, AMP, recompute — is compiled into sharding
annotations on ONE program; GSPMD inserts every collective the reference
inserted by hand (SURVEY.md §7):

  ShardingOptimizer (ZeRO-2)  → optimizer state sharded over 'dp'
                                (weight-update sharding; grads become
                                reduce-scatter + update + all-gather)
  stage-3 (new vs reference)  → params sharded over 'dp'; XLA schedules
                                gather/release around use sites
  TP split                    → PartitionSpecs carried by parallel layers
  AMP                         → bf16 compute params, fp32 master + moments
  Recompute                   → jax.checkpoint policy on the forward
  grad allreduce (DP)         → implicit: mean loss over dp-sharded batch
"""
from __future__ import annotations

import math
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..nn import ClipGradByGlobalNorm
from ..profiler import instrument as _pinstr
from ..profiler import recompile as _precomp
from ..profiler import trace as _ptrace
from ..profiler.metrics import registry as _preg
from ..static.functional import functional_call, state_tensors
from .fleet.distributed_strategy import DistributedStrategy
from .mesh import create_mesh


def build_mesh_from_strategy(strategy: DistributedStrategy,
                             devices=None) -> Mesh:
    """hybrid_configs degrees → Mesh with axes (dp, pp, tp, sp, ep)."""
    devs = list(devices if devices is not None else jax.devices())
    h = strategy.hybrid_configs
    tp = max(1, h.mp_degree)
    pp = max(1, h.pp_degree)
    sp = max(1, h.sp_degree)
    ep = max(1, getattr(h, "ep_degree", 1))
    dp = h.dp_degree if h.dp_degree > 0 else \
        len(devs) // (tp * pp * sp * ep)
    axes = {"dp": dp, "pp": pp, "tp": tp, "sp": sp}
    if ep > 1:
        axes["ep"] = ep
    return create_mesh(axes, devs)


def _spec_axes(spec: P) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _add_axis(spec: P, ndim: int, shape, axis_name: str, axis_size: int) -> P:
    """Extend `spec` by sharding `axis_name` onto the first free, divisible
    dim (for ZeRO param/opt-state sharding). Returns spec unchanged if no
    dim qualifies."""
    if axis_size <= 1 or axis_name in _spec_axes(spec):
        return spec
    entries = list(spec) + [None] * (ndim - len(spec))
    for d in range(ndim):
        e = entries[d]
        existing = () if e is None else (e if isinstance(e, tuple) else (e,))
        # callers pass `shape` already divided by the existing sharding, so
        # this check covers divisibility under composition too
        if shape[d] % axis_size != 0:
            continue
        entries[d] = tuple(existing) + (axis_name,) if existing else axis_name
        return P(*entries)
    return spec


def resolve_param_specs(layer, mesh: Mesh, zero_stage: int = 0
                        ) -> Dict[str, P]:
    """Collect PartitionSpecs: TP specs from layers' ``param_shardings``
    (distributed/parallel_layers.py), plus ZeRO-3 dp sharding."""
    pn, pt, _, _ = state_tensors(layer)
    specs = {name: P() for name in pn}
    for lname, sub in layer.named_sublayers(include_self=True):
        ps = getattr(sub, "param_shardings", None)
        if not ps:
            continue
        for local, spec in ps.items():
            gname = f"{lname}.{local}" if lname else local
            if gname in specs:
                # drop axes absent from the mesh (e.g. tp on a dp-only mesh)
                entries = []
                for e in spec:
                    if e is None:
                        entries.append(None)
                    elif isinstance(e, (tuple, list)):
                        kept = tuple(a for a in e if a in mesh.axis_names
                                     and mesh.shape[a] > 1)
                        entries.append(kept if kept else None)
                    else:
                        entries.append(e if e in mesh.axis_names
                                       and mesh.shape[e] > 1 else None)
                specs[gname] = P(*entries)
    if zero_stage >= 3 and "dp" in mesh.axis_names:
        dp = mesh.shape["dp"]
        name2tensor = dict(zip(pn, pt))
        for name in specs:
            t = name2tensor[name]
            # keep divisibility under existing tp sharding
            shape = _local_check_shape(t._value.shape, specs[name], mesh)
            specs[name] = _add_axis(specs[name], t._value.ndim, shape,
                                    "dp", dp)
    return specs


def _local_check_shape(shape, spec: P, mesh: Mesh):
    """Shape divided by existing sharding, for divisibility checks."""
    out = list(shape)
    for d, e in enumerate(spec):
        if e is None:
            continue
        axes = e if isinstance(e, (tuple, list)) else (e,)
        for a in axes:
            out[d] = out[d] // mesh.shape[a]
    return tuple(out)


def functional_clip(clip, grads):
    """Apply a grad-clip object to a pytree of gradients (traced-safe).
    Mirrors the eager apply_grad_clip (optimizer/clip.py) for the compiled
    path; supports all three reference clip types (fluid/clip.py)."""
    from ..nn import ClipGradByNorm, ClipGradByValue

    if clip is None:
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    if isinstance(clip, ClipGradByValue):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, clip.min, clip.max), grads)
    if isinstance(clip, ClipGradByNorm):
        def per_leaf(g):
            n = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
            s = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
            return (g * s).astype(g.dtype)

        return jax.tree_util.tree_map(per_leaf, grads)
    if isinstance(clip, ClipGradByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in leaves))
        scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(gn, 1e-12))
        return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype),
                                      grads)
    raise TypeError(f"Unknown grad clip type: {type(clip)}")


def make_param_update(opt):
    """Shared per-param functional update: l2/decoupled decay + opt rule.
    Used by both compiled trainers so the semantics can't drift from the
    eager Optimizer.step fused loop."""
    decay_mode = opt._decay_mode
    l2 = opt._weight_decay

    def upd(p, g, s, lr, step_no, plr=1.0, wd=0.0):
        g = g.astype(jnp.float32)
        if decay_mode == "l2" and l2:
            g = g + l2 * p
        return opt._update(p, g, s, lr * plr, step_no, wd=wd)

    return upd


def make_flat_update(opt):
    """ZeRO flat-chunk spelling of :func:`make_param_update`: the same
    decay rule + opt rule applied to the fused flat parameter slice a
    dp shard owns (qcomm.dp_zero_step). Exact by construction — every
    optimizer ``_update`` is elementwise, so updating a slice of the
    concatenation bitwise-equals slicing the per-param updates; ``plr``
    / ``wd`` arrive as scalars or per-element vectors laid out like the
    flat buffer and broadcast elementwise either way."""
    decay_mode = opt._decay_mode
    l2 = opt._weight_decay

    def upd(p, g, s, lr, step_no, plr, wd):
        g = g.astype(jnp.float32)
        if decay_mode == "l2" and l2:
            g = g + l2 * p
        return opt._update(p, g, s, lr * plr, step_no, wd=wd)

    return upd


class _FlatShim:
    """Stand-in 'parameter' handed to ``optimizer._init_state`` to
    allocate state at the ZeRO flat-slab shape (state init only reads
    ``._value``)."""

    def __init__(self, value):
        self._value = value


def _flat_knob(vals, sizes, pad_to):
    """Per-parameter scalars -> the dp_zero_step knob spelling: one
    scalar when uniform, else a flat f32 vector laid out exactly like
    the fused param buffer (zero-padded tail; pad elements get knob 0,
    which is inert — their grads are padding zeros too)."""
    vals = [float(v) for v in vals]
    if len(set(vals)) <= 1:
        return jnp.float32(vals[0] if vals else 0.0)
    vec = np.concatenate([np.full(s, v, np.float32)
                          for v, s in zip(vals, sizes)]) \
        if sizes else np.zeros(0, np.float32)
    vec = np.pad(vec, (0, pad_to - vec.size))
    return jnp.asarray(vec)


class HybridParallelTrainer:
    """Compiled SPMD training loop over (model, optimizer, strategy).

    State (params/opt-states/buffers) lives on device with its sharding;
    ``sync_to_layer()`` writes it back into the eager Layer for
    checkpointing/eval.
    """

    def __init__(self, layer, optimizer, strategy: Optional[
            DistributedStrategy] = None, mesh: Optional[Mesh] = None,
            loss_fn=None, data_spec: Optional[Tuple] = None,
            donate: bool = True, accumulate_steps: int = 1,
            dp_grad_comm: str = "f32", dp_grad_block: int = 2048,
            dp_param_comm: Optional[str] = None):
        self.layer = layer
        self.optimizer = optimizer
        # gradient merge (reference: fleet gradient_merge meta-optimizer /
        # GradMergeOptimizer): the compiled step lax.scans over
        # ``accumulate_steps`` micro-batches — each micro's backward
        # completes before the next forward (one micro's activations
        # live at a time) — and applies ONE optimizer update on the
        # mean gradient. Amortizes the optimizer-state memory traffic,
        # which dominates for expert-heavy models (round-5 MoE profile:
        # AdamW moments on 508M params cost ~12% of the step).
        self.accumulate_steps = int(accumulate_steps)
        self.strategy = strategy or DistributedStrategy()
        self.mesh = mesh if mesh is not None else \
            build_mesh_from_strategy(self.strategy)
        self.loss_fn = loss_fn
        zero = self.strategy.sharding_configs.sharding_stage if \
            self.strategy.sharding else 0
        self.zero_stage = zero
        self.amp = self.strategy.amp
        # quantized DP-gradient sync (distributed/qcomm.py, ROADMAP 3b):
        # "int8" computes per-shard local gradients inside an all-manual
        # shard_map and reduces them through the EQuARX-style compressed
        # ring (blockwise int8 transport, f32 accumulation) instead of
        # GSPMD's implicit f32 AllReduce. Pure-DP only: every non-dp
        # mesh axis must be 1.
        from . import qcomm as _qcomm

        _qcomm.validate_dp_grad_comm(dp_grad_comm, self.mesh,
                                     zero_stage=zero,
                                     block=int(dp_grad_block))
        self.dp_grad_comm = dp_grad_comm
        self.dp_grad_block = int(dp_grad_block)

        # ZeRO-1/2 manual weight-update sharding (ISSUE 19; Xu et al.
        # 2004.13336): on a pure-DP mesh, stages 1-2 run the whole
        # update inside the ONE dp shard_map — reduce-scatter grads to
        # their owner shard (quantized or f32 ring per dp_grad_comm),
        # optimizer update on only the owned flat slice (state lives
        # at shard shape: the memory win), all-gather updated params
        # back (payload per dp_param_comm). Non-pure-DP meshes keep
        # the GSPMD _add_axis spelling below; stage 3 (param sharding)
        # is GSPMD-only.
        dp = self.mesh.shape.get("dp", 1)
        pure_dp = all(s == 1 for a, s in self.mesh.shape.items()
                      if a != "dp")
        self.zero_manual = bool(zero in (1, 2) and dp > 1 and pure_dp)
        if dp_param_comm is None:
            dp_param_comm = "bf16" if (self.zero_manual
                                       and dp_grad_comm == "int8") \
                else "f32"
        _qcomm.validate_dp_param_comm(dp_param_comm, self.zero_manual)
        self.dp_param_comm = dp_param_comm
        if self.zero_manual:
            clip = optimizer._grad_clip
            if clip is not None and not isinstance(clip,
                                                   ClipGradByGlobalNorm):
                raise NotImplementedError(
                    "ZeRO sharded update supports grad clipping only "
                    "by global norm (per-leaf clips need the full "
                    f"gradient on every shard); got {type(clip).__name__}")

        pn, pt, bn, bt = state_tensors(layer)
        self.param_names, self._param_tensors = pn, pt
        self.buffer_names, self._buffer_tensors = bn, bt
        self.param_specs = resolve_param_specs(layer, self.mesh, zero)

        if self.zero_manual:
            # fused flat optimizer state, dp-sharded: ONE [dp*chunk]
            # slab per state key (+ the f32 master param copy when the
            # param all-gather is compressed — bf16 round-trip rounding
            # would swallow small updates without it)
            sizes = [int(np.prod(p._value.shape)) for p in pt]
            self._zero_sizes = sizes
            self._zero_chunk = _qcomm.zero_chunk_len(
                sum(sizes), dp, self.dp_grad_block)
            slab = dp * self._zero_chunk
            st = optimizer._init_state(
                _FlatShim(jnp.zeros((slab,), jnp.float32)))
            if self.dp_param_comm != "f32":
                flat = np.concatenate(
                    [np.asarray(p._value, np.float32).reshape(-1)
                     for p in pt]) if pt else np.zeros(0, np.float32)
                st["master"] = jnp.asarray(
                    np.pad(flat, (0, slab - flat.size)))
            dp_sh = NamedSharding(self.mesh, P("dp"))
            self.opt_states = {k: jax.device_put(v, dp_sh)
                               for k, v in st.items()}
            self.opt_specs = {k: P("dp") for k in st}
        else:
            # optimizer state: init + specs (GSPMD ZeRO>=1 shards
            # moments over dp via _add_axis)
            self.opt_states = []
            self.opt_specs = []
            for name, p in zip(pn, pt):
                s = optimizer._init_state(p)
                self.opt_states.append(s)
                pspec = self.param_specs[name]
                if zero >= 1:
                    shape = _local_check_shape(p._value.shape, pspec,
                                               self.mesh)
                    sspec = _add_axis(pspec, p._value.ndim, shape, "dp",
                                      dp)
                else:
                    sspec = pspec
                self.opt_specs.append({k: sspec for k in s})

        # place state onto the mesh
        self.params = [
            jax.device_put(p._value, NamedSharding(self.mesh,
                                                   self.param_specs[n]))
            for n, p in zip(pn, pt)]
        self.buffers = [jax.device_put(b._value,
                                       NamedSharding(self.mesh, P()))
                        for b in bt]
        if not self.zero_manual:
            self.opt_states = jax.device_put(
                self.opt_states,
                [{k: NamedSharding(self.mesh, spec[k]) for k in spec}
                 for spec in self.opt_specs])

        self.data_spec = data_spec
        self._step = 0
        self._prof_site = _precomp.unique_site("compile_train_step")
        self._build()

    # -- functional pieces -------------------------------------------------
    def _forward_loss(self, params, buffers, batch, key):
        layer = self.layer
        if self.amp:
            cast = [v.astype(jnp.bfloat16)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for v in params]
        else:
            cast = params
        if self.amp:
            # inputs follow the compute dtype (conv/matmul require matching
            # operand dtypes); int arrays pass through, and in the loss_fn
            # regime the LABEL (last element) keeps its dtype — float
            # regression/soft-label targets must not be rounded to bf16
            n_cast = len(batch) - 1 if self.loss_fn is not None \
                else len(batch)
            batch = tuple(
                b.astype(jnp.bfloat16)
                if i < n_cast and jnp.issubdtype(
                    jnp.asarray(b).dtype, jnp.floating)
                else b for i, b in enumerate(batch))
        if self.loss_fn is not None:
            with _ptrace.annotate("fwd"):
                out, new_buf = functional_call(layer, cast, buffers,
                                               batch[:-1], training=True,
                                               rng_key=key)
                loss = self.loss_fn(
                    Tensor(out) if not isinstance(out, Tensor) else out,
                    Tensor(batch[-1]))
            loss = loss._value if isinstance(loss, Tensor) else loss
        else:
            # model exposes .loss(*batch) (e.g. GPT)
            from ..core import rng as rng_mod

            pt = self._param_tensors
            bt = self._buffer_tensors
            from ..static.functional import _swapped_state

            with _swapped_state(pt + bt, list(cast) + list(buffers)):
                with rng_mod.key_scope(key), _ptrace.annotate("fwd"):
                    loss_t = layer.loss(*[Tensor(b) for b in batch])
                new_buf = [t._value for t in bt]
            loss = loss_t._value
        return loss.astype(jnp.float32), new_buf

    def _build(self):
        opt = self.optimizer
        clip = opt._grad_clip
        mesh = self.mesh

        lrs = tuple(p.optimize_attr.get("learning_rate", 1.0)
                    for p in self._param_tensors)
        wds = tuple(opt._decoupled_wd(p) for p in self._param_tensors)
        upd = make_param_update(opt)

        k_acc = self.accumulate_steps

        def local_loss_grads(params, buffers, batch, key):
            """Loss + gradients over (this shard of) ``batch`` — the
            whole logical batch on the GSPMD path, the device-local
            shard inside the dp_grad_comm='int8' shard_map."""
            if k_acc > 1:
                for b in jax.tree_util.tree_leaves(batch):
                    if b.shape[0] % k_acc:
                        raise ValueError(
                            f"gradient merge: batch size {b.shape[0]} is "
                            f"not divisible by accumulate_steps={k_acc}"
                            + (" — the PER-SHARD batch: "
                               "dp_grad_comm='int8' scans micro-batches "
                               "inside each dp shard, so the global "
                               "batch must divide dp × accumulate_steps"
                               if qcomm_dp > 1 else ""))
                micros = jax.tree_util.tree_map(
                    lambda b: b.reshape((k_acc, b.shape[0] // k_acc)
                                        + b.shape[1:]), batch)
                keys = jax.random.split(key, k_acc)

                def micro(carry, xs):
                    bufs, acc = carry
                    mb, mkey = xs

                    def loss_of(ps):
                        return self._forward_loss(ps, bufs, mb, mkey)

                    (mloss, nbuf), g = jax.value_and_grad(
                        loss_of, has_aux=True)(params)
                    acc = [a + gi.astype(a.dtype)
                           for a, gi in zip(acc, g)]
                    return (nbuf, acc), mloss

                acc0 = [jnp.zeros(p.shape, jnp.float32) for p in params]
                (new_buf, acc), mlosses = jax.lax.scan(
                    micro, (buffers, acc0), (micros, keys))
                loss = jnp.mean(mlosses)
                grads = [a / k_acc for a in acc]
            else:
                def loss_of(ps):
                    loss, new_buf = self._forward_loss(ps, buffers, batch,
                                                       key)
                    return loss, new_buf

                (loss, new_buf), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params)
            return loss, new_buf, grads

        qcomm_dp = self.mesh.shape.get("dp", 1) \
            if self.dp_grad_comm == "int8" else 1
        qcomm_block = self.dp_grad_block
        zero_manual = self.zero_manual
        zdp = self.mesh.shape.get("dp", 1)
        if zero_manual:
            from . import qcomm as _zq

            flat_upd = make_flat_update(opt)
            clip_norm = float(clip.clip_norm) if clip is not None \
                else None
            slab = zdp * self._zero_chunk
            plr_knob = _flat_knob(lrs, self._zero_sizes, slab)
            wd_knob = _flat_knob(wds, self._zero_sizes, slab)

        def step_fn(params, opt_states, buffers, batch, lr, step_no, key):
            # trace-time side effect: reports every (re)trace of this
            # program with the triggering batch shapes (profiler.recompile)
            _precomp.mark_trace(self._prof_site, batch)
            if zero_manual:
                # ZeRO-1/2 sharded update: the ONE shared shard_map
                # wrap (qcomm.dp_zero_step) does per-shard local
                # grads, fused reduce-scatter (quantized or f32 ring
                # per dp_grad_comm), global-norm clip on the reduced
                # chunks, the shard-local flat optimizer update, and
                # the param all-gather (dp_param_comm payload). Grad
                # accumulation (local_loss_grads' scan) and AMP
                # compose unchanged — they live inside `local`.
                def local(rep, params_, key_, batch_):
                    (buffers_,) = rep
                    return local_loss_grads(params_, buffers_, batch_,
                                            key_)

                bspecs = tuple(self.data_spec) \
                    if self.data_spec is not None \
                    else _zq.dp_batch_specs(batch, zdp)
                loss, new_buf, new_params, new_states = _zq.dp_zero_step(
                    mesh, zdp, self.dp_grad_block, self.dp_grad_comm,
                    self.dp_param_comm, local, flat_upd, (buffers,),
                    params, opt_states, batch, bspecs, key, lr,
                    step_no, plr_knob, wd_knob, clip_norm=clip_norm)
                return loss, new_params, new_states, new_buf
            if qcomm_dp > 1:
                # quantized DP-grad sync: per-shard local grads inside
                # the ONE shared all-manual shard_map wrap (qcomm.py),
                # reduced by the EQuARX-style compressed ring. The
                # local loss is the mean over the shard, so
                # pmean(loss) == the global mean loss and pmean(local
                # grads) == its gradient — the quantized ring replaces
                # that pmean, which is the ONLY numeric difference vs
                # the GSPMD path. An explicit data_spec is
                # authoritative (a leaf the user replicated must NOT
                # be split just because its dim 0 happens to divide
                # dp — under the manual wrap that would hand each
                # shard a slice of a non-batch array); the lead-dim
                # heuristic covers the no-spec default.
                from . import qcomm as _qcomm

                def local(rep, key_, batch_):
                    params_, buffers_ = rep
                    return local_loss_grads(params_, buffers_, batch_,
                                            key_)

                bspecs = tuple(self.data_spec) \
                    if self.data_spec is not None \
                    else _qcomm.dp_batch_specs(batch, qcomm_dp)
                loss, new_buf, grads = \
                    _qcomm.dp_quantized_value_and_grads(
                        mesh, qcomm_dp, qcomm_block, local,
                        (params, buffers), batch, bspecs, key)
            else:
                loss, new_buf, grads = local_loss_grads(
                    params, buffers, batch, key)
            grads = functional_clip(clip, grads)
            with _ptrace.annotate("optim"):
                new_params, new_states = [], []
                for p, g, s, plr, wd in zip(params, grads, opt_states,
                                            lrs, wds):
                    np_, ns = upd(p, g, s, lr, step_no, plr=plr, wd=wd)
                    new_params.append(np_)
                    new_states.append(ns)
            return loss, new_params, new_states, new_buf

        param_sh = [NamedSharding(mesh, self.param_specs[n])
                    for n in self.param_names]
        if zero_manual:
            state_sh = {k: NamedSharding(mesh, P("dp"))
                        for k in self.opt_specs}
        else:
            state_sh = [{k: NamedSharding(mesh, spec[k]) for k in spec}
                        for spec in self.opt_specs]
        buf_sh = [NamedSharding(mesh, P()) for _ in self.buffers]
        repl = NamedSharding(mesh, P())

        self._out_shardings = (repl, param_sh, state_sh, buf_sh)
        self._step_fn = jax.jit(
            step_fn,
            in_shardings=(param_sh, state_sh, buf_sh, None, None, None,
                          None),
            out_shardings=self._out_shardings,
            donate_argnums=(0, 1))

    def _shard_batch(self, batch):
        arrs = []
        for i, b in enumerate(batch):
            v = b._value if isinstance(b, Tensor) else jnp.asarray(b)
            if self.data_spec is not None:
                spec = self.data_spec[i]
            else:
                spec = P("dp") if v.ndim >= 1 and \
                    v.shape[0] % self.mesh.shape.get("dp", 1) == 0 else P()
            arrs.append(jax.device_put(v, NamedSharding(self.mesh, spec)))
        return tuple(arrs)

    def step(self, *batch) -> float:
        """Run one compiled hybrid-parallel training step; returns loss."""
        from ..core import rng as rng_mod

        self._step += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step, jnp.int32)
        key = rng_mod.next_key()
        # disabled cost: one bool read. Enabled, the step is host-timed
        # against a loss value fetch (the only truthful sync, bench.py
        # NOTE) and the train counters/memory high-water are recorded.
        if _ptrace.is_enabled():
            t0 = time.perf_counter_ns()
            with _ptrace.scope("compiled/h2d"):
                batch = self._shard_batch(batch)
            with _ptrace.scope("compiled/step"):
                loss, self.params, self.opt_states, self.buffers = \
                    self._step_fn(self.params, self.opt_states,
                                  self.buffers, batch, lr, step_no, key)
                float(np.asarray(loss))
            reg = _preg()
            reg.counter("train/steps").add(1)
            reg.counter("train/tokens").add(_pinstr.tokens_in_batch(batch))
            reg.histogram("compiled/step_ms").observe(
                (time.perf_counter_ns() - t0) / 1e6)
            _pinstr.record_memory_high_water()
        else:
            batch = self._shard_batch(batch)
            loss, self.params, self.opt_states, self.buffers = \
                self._step_fn(self.params, self.opt_states, self.buffers,
                              batch, lr, step_no, key)
        self.optimizer._global_step = self._step
        return loss

    __call__ = step

    def profile_step_phases(self, *batch, iters: int = 2,
                            trace_window: int = 0):
        """Per-phase (fwd/bwd/optim/comm) decomposition — the
        compile_train_step counterpart of
        ``HybridPipelineTrainer.profile_step_phases`` (see its docstring
        for semantics): nested prefixes fwd / fwd+bwd / full step are
        compiled and timed, comm is modeled from collective bytes, and
        the results land in the ``phase/*_ms`` gauges.
        ``trace_window=k`` wraps k more real steps in a parsed
        device-trace capture (measured per-op/per-collective timings,
        overlap fraction, MFU ledger) returned under ``"trace"``."""
        from ..core import rng as rng_mod

        vs = self._shard_batch(batch)
        key = rng_mod.next_key()

        fwd = jax.jit(lambda ps, bufs: self._forward_loss(
            ps, bufs, vs, key)[0])
        t_fwd = _pinstr.time_compiled(
            lambda: fwd(self.params, self.buffers), iters)
        fb = jax.jit(lambda ps, bufs: jax.value_and_grad(
            lambda p_: self._forward_loss(p_, bufs, vs, key),
            has_aux=True)(ps))
        t_fb = _pinstr.time_compiled(
            lambda: fb(self.params, self.buffers), iters)
        t_step = _pinstr.time_compiled(lambda: self.step(*batch), iters)

        with _precomp.suppressed():
            lowered = self._step_fn.lower(
                self.params, self.opt_states, self.buffers, vs,
                jnp.asarray(0.0, jnp.float32), jnp.asarray(0, jnp.int32),
                key)
        st = _pinstr.record_collectives_from(lowered, self.mesh)
        # same program inventory + measured/estimated comm split as
        # HybridPipelineTrainer.profile_step_phases
        from ..profiler import xla_stats as _xstats

        ps = _xstats.record_lowered(self._prof_site, lowered)
        out = _pinstr.record_phases(
            fwd_s=t_fwd, fwdbwd_s=t_fb, step_s=t_step,
            comm_bytes=st["total_bytes"],
            platform=self.mesh.devices.flat[0].platform,
            cost_bytes_accessed=ps.bytes_accessed)
        if trace_window:
            from ..profiler import device_trace as _dtrace

            with _dtrace.capture(steps=int(trace_window),
                                 label=self._prof_site) as cap:
                for _ in range(int(trace_window)):
                    _pinstr._first_leaf(self.step(*batch))
            out["trace"] = cap.summary
        return out

    def memory_ledger(self) -> dict:
        """Per-rank resident bytes by state category, from ACTUAL array
        shardings (profiler.record_memory_ledger — gauges
        ``mem/{param,grad,opt_state,master}_bytes``). On the manual
        ZeRO path opt state (and master) are [dp*chunk] slabs sharded
        P('dp'), so their per-rank count is 1/dp of the replicated
        baseline; ``grad`` is the transient fused buffer — full-size
        pre-reduce-scatter on every path, counted at the per-rank peak
        (the full flat buffer; after the scatter only the owned chunk
        stays live)."""
        cats = {"param": self.params,
                "grad": 4 * sum(int(np.prod(np.shape(p)))
                                for p in self.params)}
        if self.zero_manual:
            cats["opt_state"] = {k: v for k, v in self.opt_states.items()
                                 if k != "master"}
            if "master" in self.opt_states:
                cats["master"] = self.opt_states["master"]
        else:
            cats["opt_state"] = self.opt_states
        return _pinstr.record_memory_ledger(cats)

    def device_state(self) -> dict:
        """Device-resident training state as a pytree for
        distributed/checkpoint.py (the HybridPipelineTrainer contract):
        arrays keep their shardings, so a dp-sharded ZeRO slab saves
        per-shard and restores back to P('dp') placement."""
        return {"params": list(self.params),
                "buffers": list(self.buffers),
                "opt": self.opt_states}

    def load_device_state(self, st: dict, step: Optional[int] = None):
        """Inverse of :meth:`device_state` (restore path)."""
        self.params = list(st["params"])
        self.buffers = list(st["buffers"])
        self.opt_states = st["opt"]
        if step is not None:
            self._step = int(step)
            self.optimizer._global_step = int(step)

    def sync_to_layer(self):
        """Write device state back into the eager Layer (for save/eval)."""
        for t, v in zip(self._param_tensors, self.params):
            t._value = v
        for t, v in zip(self._buffer_tensors, self.buffers):
            t._value = v
        # hand optimizer its state back (for state_dict)
        if self.zero_manual:
            # regather the flat dp-sharded slabs and slice them back
            # into per-param state (host-side; save/eval path only)
            flat = {k: np.asarray(v) for k, v in self.opt_states.items()
                    if k != "master"}
            off = 0
            for p, sz in zip(self._param_tensors, self._zero_sizes):
                shape = p._value.shape
                self.optimizer._accumulators[id(p)] = {
                    k: jnp.asarray(v[off:off + sz].reshape(shape))
                    for k, v in flat.items()}
                off += sz
        else:
            for p, s in zip(self._param_tensors, self.opt_states):
                self.optimizer._accumulators[id(p)] = s
        return self.layer


def compile_train_step(layer, optimizer, strategy=None, mesh=None,
                       loss_fn=None, **kw) -> HybridParallelTrainer:
    return HybridParallelTrainer(layer, optimizer, strategy, mesh, loss_fn,
                                 **kw)
