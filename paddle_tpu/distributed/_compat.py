"""jax version compatibility for the distributed stack.

The package targets the modern ``jax.shard_map`` entry point (manual
axes listed via ``axis_names``, replication checking via ``check_vma``).
Older jax (< 0.5, e.g. 0.4.x) only ships
``jax.experimental.shard_map.shard_map``, whose dialect is inverted:
the body is manual over every mesh axis EXCEPT the ``auto`` complement
set, and the check flag is ``check_rep``. One shim, imported by every
shard_map call site, so the translation cannot drift per-site.
"""
from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma: bool = False):
    """``jax.shard_map`` when available, else the experimental one with
    ``axis_names``/``check_vma`` translated to ``auto``/``check_rep``.
    ``axis_names=None`` means manual over all mesh axes (both dialects'
    default)."""
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kw)
    from jax.experimental.shard_map import shard_map as _sm

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=bool(check_vma), auto=auto)
