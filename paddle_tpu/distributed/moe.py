"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

New capability vs the reference (SURVEY §2.2 confirms: "no expert
parallelism" anywhere in the tree — its MoE era came later with
incubate.distributed.models.moe built on manual alltoall ops). Designed
TPU-first per the GShard/Switch pattern:

  - experts' FFN params are stacked [E, ...] and sharded over mesh axis
    'ep' (PartitionSpec("ep", ...)); token dispatch/combine are a sorted
    scatter/gather pair — tokens are argsorted by routed expert, assigned
    capacity slots by position within their expert's segment, scattered
    into the [E, capacity, H] expert buffer and gathered back weighted by
    their gate. O(T·K·log + E·C·H) work and memory; no [T, E, C] one-hot
    ever materializes (the dense-dispatch design is ruinous at real
    expert counts). GSPMD lowers the expert-sharded scatter/gather to the
    data exchange the reference era would have hand-written with NCCL
    alltoall,
  - top-1 (Switch) or top-2 (GShard) routing with a capacity factor;
    overflow tokens fall through the residual (standard Switch behavior),
  - the Switch load-balance auxiliary loss (E * Σ_e fraction_e · prob_e)
    is exposed as ``layer.aux_loss`` for the model to add.

Composes with dp/tp/ep through the strategy compiler
(compile_train_step picks up the P("ep", ...) param_shardings and the
model.loss aux term) AND with pipeline parallelism: blocks return
``(h, aux)`` and ``pipeline_apply(stage_aux=True)`` carries the
load-balance scalar across the schedule (fill/drain ticks masked,
psum over 'pp', per-microbatch mean) — see distributed/hybrid.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import nn
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..profiler.trace import annotate as _annotate
from ..tensor._helper import apply

__all__ = ["MoEMLP", "switch_moe"]


# ---------------------------------------------------------------------------
# injective-gather dispatch/combine with gather-only VJPs
#
# Autodiff turns the dispatch gather's backward into a scatter-add — but
# within a routing round each token occupies at most ONE capacity slot
# (the map is injective), so the transpose is itself a gather through the
# inverse map. TPU gathers vectorize; row scatter-adds serialize. Both
# primitives below carry the inverse maps and declare the gather-form
# VJPs explicitly.
# ---------------------------------------------------------------------------
@jax.custom_vjp
def _dispatch_gather(x, token_of_slot, slot_of_token, valid):
    """xe_flat[s] = x[token_of_slot[s]].

    slot_of_token [K, T] (clamped), valid [K, T]: per routing round, the
    slot each token landed in. VJP: dx[t] = sum_k valid[k,t] ? g[slot_of_
    token[k,t]] : 0 — pure gathers."""
    return x[token_of_slot]


def _dispatch_fwd(x, token_of_slot, slot_of_token, valid):
    return x[token_of_slot], (slot_of_token, valid)


def _dispatch_bwd(res, g):
    slot_of_token, valid = res
    dx = None
    for k in range(slot_of_token.shape[0]):
        dk = jnp.where(valid[k][:, None], g[slot_of_token[k]], 0)
        dx = dk if dx is None else dx + dk
    return (dx, None, None, None)


_dispatch_gather.defvjp(_dispatch_fwd, _dispatch_bwd)


@jax.custom_vjp
def _combine_gather(ye, gates, slot_of_token, valid, token_of_slot,
                    round_of_slot, occupied):
    """y[t] = sum_k valid[k,t] * gates[k,t] * ye[slot_of_token[k,t]].

    VJP w.r.t. ye: dye[s] = occupied[s] ? dy[token_of_slot[s]] *
    gates[round_of_slot[s], token_of_slot[s]] : 0 — a gather (each slot
    holds one token), not the scatter-add autodiff would emit.
    """
    y = None
    for k in range(slot_of_token.shape[0]):
        w = (gates[k] * valid[k]).astype(ye.dtype)[:, None]
        c = ye[slot_of_token[k]] * w
        y = c if y is None else y + c
    return y


def _combine_fwd(ye, gates, slot_of_token, valid, token_of_slot,
                 round_of_slot, occupied):
    out = _combine_gather(ye, gates, slot_of_token, valid, token_of_slot,
                          round_of_slot, occupied)
    return out, (ye, gates, slot_of_token, valid, token_of_slot,
                 round_of_slot, occupied)


def _combine_bwd(res, dy):
    ye, gates, slot_of_token, valid, token_of_slot, round_of_slot, \
        occupied = res
    # dye: gather dy through each slot's occupying token
    wsel = gates[round_of_slot, token_of_slot].astype(ye.dtype)
    dye = jnp.where(occupied[:, None],
                    dy[token_of_slot] * wsel[:, None], 0)
    # dgates[k, t] = valid ? <dy[t], ye[slot_k_t]> : 0
    dgs = []
    for k in range(slot_of_token.shape[0]):
        contrib = jnp.sum(dy.astype(jnp.float32)
                          * ye[slot_of_token[k]].astype(jnp.float32),
                          axis=-1)
        dgs.append(jnp.where(valid[k], contrib, 0.0))
    dgates = jnp.stack(dgs)
    return (dye, dgates, None, None, None, None, None)


_combine_gather.defvjp(_combine_fwd, _combine_bwd)


def switch_moe(x, gate_w, w_in, b_in, w_out, b_out, *, top_k=1,
               capacity_factor=1.25):
    """Pure-jax MoE FFN. x: [T, H]; gate_w: [H, E]; experts stacked
    w_in [E, H, F], b_in [E, F], w_out [E, F, H], b_out [E, H].

    Returns (y [T, H], aux_loss scalar).
    """
    t, h = x.shape
    e = gate_w.shape[1]
    cap = max(1, int(np.ceil(capacity_factor * top_k * t / e)))

    # All routing math runs in the TRANSPOSED [E, T] layout: with E of 8
    # and T of thousands, [T, E] puts the long axis on sublanes and an
    # 8-wide minor dim on the 128-lane VPU — every softmax/argmax/cumsum
    # wastes 94% of the lanes (round-5 profile: the routing pipeline cost
    # more than the expert FFN fwd+bwd). [E, T] keeps T on the lanes.
    # moe/* named scopes: routing/dispatch/experts/combine phase names
    # traced into the program for device-time attribution (profiler)
    with _annotate("moe/route"):
        logits_t = jnp.dot(gate_w.astype(x.dtype).T, x.T)  # [E, T]
        probs_t = jax.nn.softmax(logits_t.astype(jnp.float32), axis=0)

        # -- routing: top_k rounds over [E, T] (never [T, E, C]) ----------
        expert_rounds, gate_rounds = [], []
        remaining = probs_t
        aux_fraction = jnp.zeros((e,), jnp.float32)
        for _ in range(top_k):
            idx = jnp.argmax(remaining, axis=0)            # [T]
            onehot_t = (jnp.arange(e, dtype=jnp.int32)[:, None]
                        == idx[None, :]).astype(jnp.float32)   # [E, T]
            expert_rounds.append(idx.astype(jnp.int32))
            gate_rounds.append(jnp.sum(remaining * onehot_t, axis=0))
            aux_fraction = aux_fraction + jnp.mean(onehot_t, axis=1)
            remaining = remaining * (1.0 - onehot_t)

    # -- dispatch: cumsum slot assignment, gather-only data movement ------
    # Round-4 profile: the argsort([K*T]) bitonic network + two full-row
    # H-wide scatters dominated the step (MoE MFU 0.29). Slot-within-
    # expert is just "how many earlier entries routed here", which a
    # [T, E] cumsum answers directly (GShard position_in_expert); earlier
    # routing rounds take earlier capacity slots via a running per-expert
    # offset. The only scatter left is int32 token ids into [E*cap]; the
    # wide data movement is a gather in (x[token_of_slot]) and a gather
    # out per round — TPU gathers vectorize, row scatters serialize.
    prior = jnp.zeros((e,), jnp.float32)                   # slots used
    slot_rounds, keep_rounds = [], []
    for k in range(top_k):
        onehot_t = (jnp.arange(e, dtype=jnp.int32)[:, None]
                    == expert_rounds[k][None, :]).astype(jnp.float32)
        pos_in_round = (jnp.cumsum(onehot_t, axis=1)
                        - onehot_t)                        # [E, T]
        pos = (jnp.sum(pos_in_round * onehot_t, axis=0)
               + prior[expert_rounds[k]]).astype(jnp.int32)  # [T]
        prior = prior + jnp.sum(onehot_t, axis=1)
        keep = pos < cap
        # overflow entries target row E*cap, dropped by scatter mode="drop"
        slot_rounds.append(jnp.where(keep, expert_rounds[k] * cap + pos,
                                     e * cap))
        keep_rounds.append(keep)

    slot_flat = jnp.concatenate(slot_rounds)               # [K*T]
    token_flat = jnp.tile(jnp.arange(t, dtype=jnp.int32), top_k)
    round_flat = jnp.repeat(jnp.arange(top_k, dtype=jnp.int32), t)
    token_of_slot = jnp.zeros((e * cap + 1,), jnp.int32).at[slot_flat] \
        .set(token_flat, mode="drop")[:e * cap]
    round_of_slot = jnp.zeros((e * cap + 1,), jnp.int32).at[slot_flat] \
        .set(round_flat, mode="drop")[:e * cap]
    occupied = jnp.zeros((e * cap + 1,), bool).at[slot_flat] \
        .set(True, mode="drop")[:e * cap]
    slot_of_token = jnp.stack(
        [jnp.minimum(s, e * cap - 1) for s in slot_rounds])  # [K, T]
    valid = jnp.stack(keep_rounds)                           # [K, T]

    with _annotate("moe/dispatch"):
        xe = _dispatch_gather(x, token_of_slot, slot_of_token,
                              valid).reshape(e, cap, h)
    # empty slots compute x[0]'s row; no token combines them and the
    # combine VJP masks them, so no spurious weight gradient flows
    with _annotate("moe/experts"):
        hmid = jax.nn.gelu(
            jnp.einsum("ech,ehf->ecf", xe, w_in.astype(x.dtype))
            + b_in.astype(x.dtype)[:, None, :])
        ye = (jnp.einsum("ecf,efh->ech", hmid, w_out.astype(x.dtype))
              + b_out.astype(x.dtype)[:, None, :]).reshape(e * cap, h)

    # -- combine: per-round gather of each token's slot, gate-weighted ----
    with _annotate("moe/combine"):
        gates = jnp.stack(gate_rounds)                       # [K, T] f32
        y = _combine_gather(ye, gates, slot_of_token, valid, token_of_slot,
                            round_of_slot, occupied)

    # Switch aux loss: E * sum_e fraction_e * mean-prob_e
    aux = e * jnp.sum((aux_fraction / top_k)
                      * jnp.mean(probs_t, axis=1))
    return y, aux.astype(jnp.float32)


class MoEMLP(nn.Layer):
    """Drop-in MoE replacement for a transformer FFN block.

    forward(x [B, S, H]) -> [B, S, H]; the load-balance loss of the last
    forward is at ``self.aux_loss`` (Tensor scalar).
    """

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, top_k: int = 1,
                 capacity_factor: float = 1.25,
                 initializer_range: float = 0.02):
        super().__init__()
        init = I.Normal(0.0, initializer_range)
        zeros = I.Constant(0.0)
        e, h, f = num_experts, hidden_size, ffn_hidden_size
        self.num_experts = e
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = self.create_parameter([h, e], default_initializer=init)
        self.w_in = self.create_parameter([e, h, f],
                                          default_initializer=init)
        self.b_in = self.create_parameter([e, f],
                                          default_initializer=zeros)
        self.w_out = self.create_parameter([e, f, h],
                                           default_initializer=init)
        self.b_out = self.create_parameter([e, h],
                                           default_initializer=zeros)
        # expert dim sharded over 'ep' (strategy compiler consumes these)
        self.param_shardings = {
            "gate": P(), "w_in": P("ep", None, None),
            "b_in": P("ep", None), "w_out": P("ep", None, None),
            "b_out": P("ep", None)}
        self._aux = Tensor(jnp.zeros((), jnp.float32))

    def forward(self, x):
        b, s, h = x.shape[0], x.shape[1], x.shape[2]

        def f(xv, gw, wi, bi, wo, bo):
            y, aux = switch_moe(
                xv.reshape(b * s, h), gw, wi, bi, wo, bo,
                top_k=self.top_k, capacity_factor=self.capacity_factor)
            return y.reshape(b, s, h), aux

        out = apply(f, x, self.gate, self.w_in, self.b_in, self.w_out,
                    self.b_out, name="moe_mlp")
        y, aux = out
        self._aux = aux
        return y

    @property
    def aux_loss(self):
        """Load-balance loss of the last forward. Inside the same trace
        (GPT.loss under jit) this is the traced value; reading a value
        LEFT OVER from a finished compiled step eagerly is an error —
        raise a clear message instead of jax's UnexpectedTracerError."""
        try:  # private jax API; on a rename fall back to jax's own error
            from jax._src.core import trace_state_clean
        except ImportError:
            def trace_state_clean():
                return False

        v = self._aux
        if isinstance(v._value, jax.core.Tracer) and trace_state_clean():
            raise RuntimeError(
                "MoEMLP.aux_loss of the last compiled step is not "
                "readable eagerly: the value lived inside the jit trace. "
                "Fold it into the jitted loss (models/gpt.py GPT.loss "
                "does) or run the layer eagerly.")
        return v

    @aux_loss.setter
    def aux_loss(self, v):
        self._aux = v
