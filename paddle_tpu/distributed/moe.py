"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

New capability vs the reference (SURVEY §2.2 confirms: "no expert
parallelism" anywhere in the tree — its MoE era came later with
incubate.distributed.models.moe built on manual alltoall ops). Designed
TPU-first per the GShard/Switch pattern:

  - experts' FFN params are stacked [E, ...] and sharded over mesh axis
    'ep' (PartitionSpec("ep", ...)); token dispatch/combine are a sorted
    scatter/gather pair — tokens are argsorted by routed expert, assigned
    capacity slots by position within their expert's segment, scattered
    into the [E, capacity, H] expert buffer and gathered back weighted by
    their gate. O(T·K·log + E·C·H) work and memory; no [T, E, C] one-hot
    ever materializes (the dense-dispatch design is ruinous at real
    expert counts). GSPMD lowers the expert-sharded scatter/gather to the
    data exchange the reference era would have hand-written with NCCL
    alltoall,
  - top-1 (Switch) or top-2 (GShard) routing with a capacity factor;
    overflow tokens fall through the residual (standard Switch behavior),
  - the Switch load-balance auxiliary loss (E * Σ_e fraction_e · prob_e)
    is exposed as ``layer.aux_loss`` for the model to add.

Composes with dp/tp/ep through the strategy compiler
(compile_train_step picks up the P("ep", ...) param_shardings and the
model.loss aux term) AND with pipeline parallelism: blocks return
``(h, aux)`` and ``pipeline_apply(stage_aux=True)`` carries the
load-balance scalar across the schedule (fill/drain ticks masked,
psum over 'pp', per-microbatch mean) — see distributed/hybrid.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import nn
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor._helper import apply

__all__ = ["MoEMLP", "switch_moe"]


def switch_moe(x, gate_w, w_in, b_in, w_out, b_out, *, top_k=1,
               capacity_factor=1.25):
    """Pure-jax MoE FFN. x: [T, H]; gate_w: [H, E]; experts stacked
    w_in [E, H, F], b_in [E, F], w_out [E, F, H], b_out [E, H].

    Returns (y [T, H], aux_loss scalar).
    """
    t, h = x.shape
    e = gate_w.shape[1]
    cap = max(1, int(np.ceil(capacity_factor * top_k * t / e)))

    logits = jnp.dot(x, gate_w.astype(x.dtype))            # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # -- routing: top_k rounds over [T, E] (never [T, E, C]) --------------
    expert_rounds, gate_rounds = [], []
    remaining = probs
    aux_fraction = jnp.zeros((e,), jnp.float32)
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)               # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, E]
        expert_rounds.append(idx.astype(jnp.int32))
        gate_rounds.append(jnp.sum(remaining * onehot, axis=-1))
        aux_fraction = aux_fraction + jnp.mean(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)

    # -- dispatch: sort (token, round) pairs by expert --------------------
    # round-major flattening + stable sort = earlier routing rounds get
    # earlier capacity slots, tokens in order within a round (so a round-1
    # and a round-2 token on the same expert never collide on a slot)
    expert_flat = jnp.concatenate(expert_rounds)           # [K*T]
    gate_flat = jnp.concatenate(gate_rounds)               # [K*T] f32
    token_flat = jnp.tile(jnp.arange(t, dtype=jnp.int32), top_k)

    order = jnp.argsort(expert_flat, stable=True)
    e_sorted = expert_flat[order]
    tok_sorted = token_flat[order]
    gate_sorted = gate_flat[order]
    # slot within the expert = position within its sorted segment
    counts = jax.ops.segment_sum(
        jnp.ones_like(e_sorted), e_sorted, num_segments=e,
        indices_are_sorted=True)                           # [E]
    seg_start = jnp.cumsum(counts) - counts                # exclusive
    pos = jnp.arange(top_k * t, dtype=jnp.int32) - seg_start[e_sorted]
    keep = pos < cap
    # overflow entries target row E*cap, dropped by scatter mode="drop"
    slot = jnp.where(keep, e_sorted * cap + pos, e * cap)

    xe = jnp.zeros((e * cap, h), x.dtype).at[slot].set(
        x[tok_sorted], mode="drop").reshape(e, cap, h)
    hmid = jax.nn.gelu(
        jnp.einsum("ech,ehf->ecf", xe, w_in.astype(x.dtype))
        + b_in.astype(x.dtype)[:, None, :])
    ye = (jnp.einsum("ecf,efh->ech", hmid, w_out.astype(x.dtype))
          + b_out.astype(x.dtype)[:, None, :]).reshape(e * cap, h)

    # -- combine: gather each entry's expert output, weight by its gate ---
    w = (gate_sorted * keep).astype(x.dtype)[:, None]
    contrib = ye[jnp.minimum(slot, e * cap - 1)] * w
    y = jnp.zeros((t, h), x.dtype).at[tok_sorted].add(contrib)

    # Switch aux loss: E * sum_e fraction_e * mean-prob_e
    aux = e * jnp.sum((aux_fraction / top_k)
                      * jnp.mean(probs, axis=0))
    return y, aux.astype(jnp.float32)


class MoEMLP(nn.Layer):
    """Drop-in MoE replacement for a transformer FFN block.

    forward(x [B, S, H]) -> [B, S, H]; the load-balance loss of the last
    forward is at ``self.aux_loss`` (Tensor scalar).
    """

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, top_k: int = 1,
                 capacity_factor: float = 1.25,
                 initializer_range: float = 0.02):
        super().__init__()
        init = I.Normal(0.0, initializer_range)
        zeros = I.Constant(0.0)
        e, h, f = num_experts, hidden_size, ffn_hidden_size
        self.num_experts = e
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = self.create_parameter([h, e], default_initializer=init)
        self.w_in = self.create_parameter([e, h, f],
                                          default_initializer=init)
        self.b_in = self.create_parameter([e, f],
                                          default_initializer=zeros)
        self.w_out = self.create_parameter([e, f, h],
                                           default_initializer=init)
        self.b_out = self.create_parameter([e, h],
                                           default_initializer=zeros)
        # expert dim sharded over 'ep' (strategy compiler consumes these)
        self.param_shardings = {
            "gate": P(), "w_in": P("ep", None, None),
            "b_in": P("ep", None), "w_out": P("ep", None, None),
            "b_out": P("ep", None)}
        self._aux = Tensor(jnp.zeros((), jnp.float32))

    def forward(self, x):
        b, s, h = x.shape[0], x.shape[1], x.shape[2]

        def f(xv, gw, wi, bi, wo, bo):
            y, aux = switch_moe(
                xv.reshape(b * s, h), gw, wi, bi, wo, bo,
                top_k=self.top_k, capacity_factor=self.capacity_factor)
            return y.reshape(b, s, h), aux

        out = apply(f, x, self.gate, self.w_in, self.b_in, self.w_out,
                    self.b_out, name="moe_mlp")
        y, aux = out
        self._aux = aux
        return y

    @property
    def aux_loss(self):
        """Load-balance loss of the last forward. Inside the same trace
        (GPT.loss under jit) this is the traced value; reading a value
        LEFT OVER from a finished compiled step eagerly is an error —
        raise a clear message instead of jax's UnexpectedTracerError."""
        try:  # private jax API; on a rename fall back to jax's own error
            from jax._src.core import trace_state_clean
        except ImportError:
            def trace_state_clean():
                return False

        v = self._aux
        if isinstance(v._value, jax.core.Tracer) and trace_state_clean():
            raise RuntimeError(
                "MoEMLP.aux_loss of the last compiled step is not "
                "readable eagerly: the value lived inside the jit trace. "
                "Fold it into the jitted loss (models/gpt.py GPT.loss "
                "does) or run the layer eagerly.")
        return v

    @aux_loss.setter
    def aux_loss(self, v):
        self._aux = v
