"""Elastic training: periodic async checkpoint + resume-from-latest,
with an asynchronous step pipeline (ISSUE 3 tentpole).

SURVEY §5 names checkpoint-restart elasticity a design-from-day-one goal
and a capability to SURPASS the reference, whose launcher only tears the
job down on failure (reference: fleet/launch_utils.py:295
terminate_local_procs; the `elastic` strategy bit is unused,
framework/distributed_strategy.proto:133). Here:

  - every ``save_interval`` steps the trainer's sharded device state goes
    through the async checkpoint (distributed/checkpoint.py) — training
    continues while bytes hit disk;
  - the checkpoint meta carries step, the framework RNG stream state, and
    the REAL data cursor (which may run ahead of the step count after a
    resilience rollback skipped poisoned batches), so a
    killed-and-restarted run continues the EXACT loss curve
    (deterministic data order + RNG semantics, SURVEY §7 "loss-curve
    parity" hard part);
  - ``ElasticTrainer.run`` resumes from the newest COMMITTED step: a kill
    mid-save lands on the previous one (COMMIT-marker crash consistency);
    with ``degraded_restore`` (default) a CORRUPT newest step walks back
    to an older committed one instead of killing the restart
    (checkpoint.restore_degraded, ``resilience/restore_fallbacks``).

Async step pipeline (the three host tails hidden behind compute):

  1. **deferred loss sync** (``async_dispatch``): ``trainer.step``
     returns the loss as a device future; the loop keeps a bounded
     in-flight window (``max_inflight``, default 2) of unmaterialized
     losses so step N+1's host dispatch overlaps step N's device
     execution, and only syncs at ``sync_interval`` boundaries, window
     overflow, save points, and run end. The dispatched program is
     bit-identical to synchronous mode — only WHEN the host reads the
     scalar changes, so clean-run loss curves match bitwise.
  2. **input prefetch** (``prefetch_depth``): a background producer
     (distributed/prefetch.py) runs ``data_fn(cursor)`` and the
     trainer's H2D staging for upcoming cursors while the current step
     executes. Cursor-accurate: a rollback invalidates the in-flight
     window.
  3. **streamed checkpoint snapshots** (``snapshot_async``): saves
     copy device state to host in bounded chunks on the writer thread;
     the loop passes the ``wait_snapshot`` gate before the next step
     dispatch (the step donates the saved buffers), so the D2H
     overlaps data fetch/staging/loss syncs instead of blocking the
     loop inline. COMMIT/kill-mid-save semantics unchanged
     (checkpoint.save docstring).

Usage::

    tr = HybridPipelineTrainer(model, opt, strategy, mesh)
    el = ElasticTrainer(tr, ckpt_dir, save_interval=100)
    el.run(data_fn, total_steps)   # data_fn(cursor) -> batch tuple
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import rng as rng_mod
from ..profiler import trace as _ptrace
from ..profiler.metrics import registry as _registry
from .checkpoint import CheckpointManager, all_steps, load_meta

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    def __init__(self, trainer, ckpt_dir: str, save_interval: int = 100,
                 keep: int = 2, degraded_restore: bool = True,
                 verify_restore: bool = False,
                 async_dispatch: bool = False, sync_interval: int = 8,
                 max_inflight: int = 2, prefetch_depth: int = 0,
                 snapshot_async: bool = False,
                 snapshot_chunk_bytes: Optional[int] = None):
        self.trainer = trainer
        self.save_interval = save_interval
        ckpt_kw = {}
        if snapshot_chunk_bytes is not None:
            ckpt_kw["snapshot_chunk_bytes"] = int(snapshot_chunk_bytes)
        self.manager = CheckpointManager(ckpt_dir, keep=keep,
                                         snapshot_async=snapshot_async,
                                         **ckpt_kw)
        # async step pipeline knobs (module docstring; README "Async
        # step pipeline" documents the interaction table)
        self.async_dispatch = bool(async_dispatch)
        self.sync_interval = max(1, int(sync_interval))
        self.max_inflight = max(1, int(max_inflight))
        self.prefetch_depth = max(0, int(prefetch_depth))
        # degraded_restore: resume() walks back past unreadable newest
        # steps instead of raising. verify_restore: CRC-check shard
        # files on restore (the walk-back can only SEE silent bit flips
        # when this is on; the resilience runner enables it).
        self.degraded_restore = degraded_restore
        self.verify_restore = verify_restore
        # the data cursor is REAL state, not an alias of step: a
        # resilience rollback re-seeds it past a poisoned batch, after
        # which cursor > step forever. data_fn(cursor) -> batch.
        self.data_cursor = 0
        # meta of the checkpoint the last resume() restored (extra keys
        # like the resilience runner's skipped_cursors ride here)
        self.last_meta: dict = {}
        # host materializations of device losses this trainer performed
        # (the CI perf-smoke asserts async_dispatch keeps this well
        # below one per step)
        self.loss_syncs = 0

    # -- state capture -----------------------------------------------------
    def _meta(self, step: int, extra=None) -> dict:
        key = np.asarray(rng_mod.get_rng_state())
        meta = {"step": int(step),
                "rng_key": key.tolist(),
                "rng_dtype": str(key.dtype),
                "data_cursor": int(self.data_cursor)}
        if extra:
            meta.update(extra)
        return meta

    def _restore_rng(self, meta: dict) -> None:
        key = np.asarray(meta["rng_key"],
                         dtype=np.dtype(meta.get("rng_dtype", "uint32")))
        rng_mod.set_rng_state(key)

    # -- resume ------------------------------------------------------------
    def resume(self, max_step: Optional[int] = None) -> int:
        """Restore the newest readable committed checkpoint; returns the
        step to continue FROM (0 if none). Restores the trainer state,
        the RNG stream, and the data cursor. ``max_step`` caps the
        restore target (newest committed step ``<= max_step``) — the
        mesh-agreed rollback passes the consensus target here so every
        rank lands on the SAME step even when some committed ahead of
        the bad streak (resilience/runner.py state-lockstep)."""
        template = self.trainer.device_state()
        if self.degraded_restore:
            state, meta, step = self.manager.restore_degraded(
                template, verify=self.verify_restore, max_step=max_step)
            if step is None:
                return 0
        else:
            step = self.manager.latest_step()
            if max_step is not None:
                eligible = [s for s in all_steps(self.manager.directory)
                            if s <= max_step]
                step = eligible[-1] if eligible else None
            if step is None:
                return 0
            state = self.manager.restore(template, step=step,
                                         verify=self.verify_restore)
            meta = load_meta(self.manager.directory, step)
        self.trainer.load_device_state(state, step=step)
        self.last_meta = dict(meta or {})
        if meta:
            self._restore_rng(meta)
            # pre-cursor checkpoints carried only step; cursor == step
            # was exact for them (no rollback machinery existed)
            self.data_cursor = int(meta.get("data_cursor", step))
        else:
            self.data_cursor = int(step)
        return int(step)

    # -- checkpointing -----------------------------------------------------
    def save(self, step: int, extra=None, async_: bool = True):
        return self.manager.save(step, self.trainer.device_state(),
                                 meta=self._meta(step, extra),
                                 async_=async_)

    # -- async step pipeline helpers ---------------------------------------
    def _sync_loss(self, dev) -> float:
        """Materialize one device loss (the ONLY host←device sync of the
        loop). The ``hybrid/sync_wait`` span measures how long the host
        actually waited — with async dispatch most of the execution
        already happened underneath the later dispatches, so this span
        shrinking (vs the synchronous per-step wait) IS the win."""
        with _ptrace.scope("hybrid/sync_wait"):
            v = float(np.asarray(dev))
        self.loss_syncs += 1
        if _ptrace.is_enabled():
            _registry().counter("elastic/loss_syncs").add(1)
        return v

    def _stage_for_prefetch(self, batch: tuple) -> tuple:
        """H2D staging hook for the background prefetcher: the trainer's
        own ``_stage_batch`` (so step() hits already-placed arrays and
        the device_put is a no-op), raw pass-through before the first
        step has built the program (batch shardings unknown until then)
        or for trainers without the staging surface."""
        stage = getattr(self.trainer, "_stage_batch", None)
        if stage is None or getattr(self.trainer, "_step_fn", None) is None:
            return batch
        return stage(batch)

    # -- the loop ----------------------------------------------------------
    def run(self, data_fn, total_steps: int, on_step=None) -> list:
        """data_fn(cursor) -> batch tuple (the deterministic data
        cursor: batch content is a pure function of the cursor, which
        equals the global step until a rollback skips batches). Returns
        the per-step losses of THIS process lifetime.

        With ``async_dispatch`` the losses (and ``on_step`` calls) are
        materialized at sync points — window overflow (``max_inflight``),
        ``sync_interval`` boundaries, save points, run end — in step
        order; the values are bitwise-identical to synchronous mode.

        NOTE: ResilientRunner.run implements its own copy of this
        window/drain/prefetch/gate sequencing — its drain interleaves
        the bad-step/rollback accounting, which this plain loop has no
        notion of. A semantic change to the window here (sync points,
        gate placement) almost certainly needs the same change there."""
        start = self.resume()
        losses: list = []
        pending: list = []               # (step, device loss future)

        def drain(keep: int = 0) -> None:
            while len(pending) > keep:
                s, dev = pending.pop(0)
                v = self._sync_loss(dev)
                losses.append(v)
                if on_step is not None:
                    on_step(s, v)

        # async dispatch must also stop a PROFILED trainer step from
        # forcing its own per-step loss sync (hybrid.py profiled_step_
        # sync) — the deferred drain below records the honest
        # hybrid/sync_wait span instead. Restored on exit: a later
        # direct profiling of the same trainer must get the default.
        prev_profiled_sync = getattr(self.trainer, "profiled_step_sync",
                                     True)
        self.trainer.profiled_step_sync = not self.async_dispatch
        prefetcher = None
        if self.prefetch_depth > 0:
            from .prefetch import BatchPrefetcher

            prefetcher = BatchPrefetcher(
                data_fn, stage=self._stage_for_prefetch,
                depth=self.prefetch_depth).start(self.data_cursor)
        try:
            for step in range(start, total_steps):
                if prefetcher is not None:
                    batch = prefetcher.get(self.data_cursor)
                else:
                    batch = data_fn(self.data_cursor)
                    if not isinstance(batch, tuple):
                        batch = (batch,)
                # streamed-snapshot gate LAST before the dispatch (which
                # DONATES the state an in-flight save may still be
                # copying out): everything above — data fetch, H2D
                # staging — overlaps the snapshot's D2H
                self.manager.wait_snapshot()
                loss = self.trainer.step(*batch)
                self.data_cursor += 1
                pending.append((step, loss))
                done = step + 1
                if not self.async_dispatch:
                    drain()
                elif done % self.sync_interval == 0:
                    drain()
                else:
                    drain(keep=self.max_inflight)
                if done % self.save_interval == 0 or done == total_steps:
                    drain()          # losses land before their save
                    self.save(done)
        finally:
            self.trainer.profiled_step_sync = prev_profiled_sync
            if prefetcher is not None:
                prefetcher.stop()
        drain()
        self.manager.wait()
        return losses
