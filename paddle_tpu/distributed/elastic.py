"""Elastic training: periodic async checkpoint + resume-from-latest.

SURVEY §5 names checkpoint-restart elasticity a design-from-day-one goal
and a capability to SURPASS the reference, whose launcher only tears the
job down on failure (reference: fleet/launch_utils.py:295
terminate_local_procs; the `elastic` strategy bit is unused,
framework/distributed_strategy.proto:133). Here:

  - every ``save_interval`` steps the trainer's sharded device state goes
    through the async checkpoint (distributed/checkpoint.py) — training
    continues while bytes hit disk;
  - the checkpoint meta carries step, the framework RNG stream state, and
    the REAL data cursor (which may run ahead of the step count after a
    resilience rollback skipped poisoned batches), so a
    killed-and-restarted run continues the EXACT loss curve
    (deterministic data order + RNG semantics, SURVEY §7 "loss-curve
    parity" hard part);
  - ``ElasticTrainer.run`` resumes from the newest COMMITTED step: a kill
    mid-save lands on the previous one (COMMIT-marker crash consistency);
    with ``degraded_restore`` (default) a CORRUPT newest step walks back
    to an older committed one instead of killing the restart
    (checkpoint.restore_degraded, ``resilience/restore_fallbacks``).

Usage::

    tr = HybridPipelineTrainer(model, opt, strategy, mesh)
    el = ElasticTrainer(tr, ckpt_dir, save_interval=100)
    el.run(data_fn, total_steps)   # data_fn(cursor) -> batch tuple
"""
from __future__ import annotations

import numpy as np

from ..core import rng as rng_mod
from .checkpoint import CheckpointManager, load_meta

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    def __init__(self, trainer, ckpt_dir: str, save_interval: int = 100,
                 keep: int = 2, degraded_restore: bool = True,
                 verify_restore: bool = False):
        self.trainer = trainer
        self.save_interval = save_interval
        self.manager = CheckpointManager(ckpt_dir, keep=keep)
        # degraded_restore: resume() walks back past unreadable newest
        # steps instead of raising. verify_restore: CRC-check shard
        # files on restore (the walk-back can only SEE silent bit flips
        # when this is on; the resilience runner enables it).
        self.degraded_restore = degraded_restore
        self.verify_restore = verify_restore
        # the data cursor is REAL state, not an alias of step: a
        # resilience rollback re-seeds it past a poisoned batch, after
        # which cursor > step forever. data_fn(cursor) -> batch.
        self.data_cursor = 0
        # meta of the checkpoint the last resume() restored (extra keys
        # like the resilience runner's skipped_cursors ride here)
        self.last_meta: dict = {}

    # -- state capture -----------------------------------------------------
    def _meta(self, step: int, extra=None) -> dict:
        key = np.asarray(rng_mod.get_rng_state())
        meta = {"step": int(step),
                "rng_key": key.tolist(),
                "rng_dtype": str(key.dtype),
                "data_cursor": int(self.data_cursor)}
        if extra:
            meta.update(extra)
        return meta

    def _restore_rng(self, meta: dict) -> None:
        key = np.asarray(meta["rng_key"],
                         dtype=np.dtype(meta.get("rng_dtype", "uint32")))
        rng_mod.set_rng_state(key)

    # -- resume ------------------------------------------------------------
    def resume(self) -> int:
        """Restore the newest readable committed checkpoint; returns the
        step to continue FROM (0 if none). Restores the trainer state,
        the RNG stream, and the data cursor."""
        template = self.trainer.device_state()
        if self.degraded_restore:
            state, meta, step = self.manager.restore_degraded(
                template, verify=self.verify_restore)
            if step is None:
                return 0
        else:
            step = self.manager.latest_step()
            if step is None:
                return 0
            state = self.manager.restore(template, step=step,
                                         verify=self.verify_restore)
            meta = load_meta(self.manager.directory, step)
        self.trainer.load_device_state(state, step=step)
        self.last_meta = dict(meta or {})
        if meta:
            self._restore_rng(meta)
            # pre-cursor checkpoints carried only step; cursor == step
            # was exact for them (no rollback machinery existed)
            self.data_cursor = int(meta.get("data_cursor", step))
        else:
            self.data_cursor = int(step)
        return int(step)

    # -- checkpointing -----------------------------------------------------
    def save(self, step: int, extra=None, async_: bool = True):
        return self.manager.save(step, self.trainer.device_state(),
                                 meta=self._meta(step, extra),
                                 async_=async_)

    # -- the loop ----------------------------------------------------------
    def run(self, data_fn, total_steps: int, on_step=None) -> list:
        """data_fn(cursor) -> batch tuple (the deterministic data
        cursor: batch content is a pure function of the cursor, which
        equals the global step until a rollback skips batches). Returns
        the per-step losses of THIS process lifetime."""
        start = self.resume()
        losses = []
        for step in range(start, total_steps):
            batch = data_fn(self.data_cursor)
            if not isinstance(batch, tuple):
                batch = (batch,)
            loss = self.trainer.step(*batch)
            self.data_cursor += 1
            losses.append(float(np.asarray(loss)))
            done = step + 1
            if done % self.save_interval == 0 or done == total_steps:
                self.save(done)
            if on_step is not None:
                on_step(step, losses[-1])
        self.manager.wait()
        return losses
