"""Distributed metric aggregation (reference:
python/paddle/distributed/fleet/metrics/metric.py:22-195 — sum/max/min/auc
over the RoleMaker's Gloo allreduce).

TPU-native translation: the aggregation rides the eager collective API
(distributed/collective.py — host-staged allreduce over the jax
coordination service), so it works in every regime the reference's Gloo
path did; inside a pjit'd eval loop the same reductions are jnp.sum +
lax.psum and need no helper.
"""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor
from ..collective import ReduceOp, all_reduce
from ..env import get_world_size

__all__ = ["sum", "max", "min", "acc", "auc"]

_builtin_sum, _builtin_max, _builtin_min = sum, max, min


def _allreduce_np(arr: np.ndarray, op) -> np.ndarray:
    if get_world_size() <= 1:
        return arr
    t = Tensor(np.ascontiguousarray(arr))
    all_reduce(t, op=op)
    return np.asarray(t._value)


def _as_array(input) -> np.ndarray:
    """Accept a Tensor, numpy/jax array, plain Python scalar, or (nested)
    list — everything np.asarray digests (the reference took raw Gloo
    buffers; callers here hand in whatever their loop accumulated)."""
    if isinstance(input, Tensor):
        input = input._value
    return np.asarray(input, np.float64)


def _scalar_or_array(out: np.ndarray):
    """0-d reductions come back as Python floats (``fm.sum(loss)`` is
    directly printable/comparable); array inputs keep their shape."""
    return float(out) if out.ndim == 0 else out


def sum(input, scope=None, util=None):  # noqa: A001
    """reference: fleet/metrics/metric.py sum(:22)."""
    return _scalar_or_array(_allreduce_np(_as_array(input), ReduceOp.SUM))


def max(input, scope=None, util=None):  # noqa: A001
    """reference: fleet/metrics/metric.py max(:57)."""
    return _scalar_or_array(_allreduce_np(_as_array(input), ReduceOp.MAX))


def min(input, scope=None, util=None):  # noqa: A001
    """reference: fleet/metrics/metric.py min(:92)."""
    return _scalar_or_array(_allreduce_np(_as_array(input), ReduceOp.MIN))


def acc(correct, total, scope=None, util=None):
    """reference: fleet/metrics/metric.py acc(:127) — global correct/total."""
    c = sum(correct)
    t = sum(total)
    return float(c) / _builtin_max(float(t), 1.0)


def auc(stat_pos, stat_neg, scope=None, util=None):
    """reference: fleet/metrics/metric.py auc(:162) — allreduce the
    positive/negative histograms then integrate (same math as
    paddle_tpu.metric.Auc.accumulate)."""
    pos = _allreduce_np(_as_array(stat_pos), ReduceOp.SUM)
    neg = _allreduce_np(_as_array(stat_neg), ReduceOp.SUM)
    tot_pos = tot_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_pos + tot_pos) * (new_neg - tot_neg) / 2
        tot_pos, tot_neg = new_pos, new_neg
    return area / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


def distributed_metric(metric):
    """Aggregate a paddle_tpu.metric.Metric across processes in place
    (Accuracy/Precision/Recall/Auc), then return metric.accumulate()."""
    from ...metric import Accuracy, Auc, Precision, Recall

    if isinstance(metric, Accuracy):
        metric.total = [int(x) for x in sum(np.asarray(metric.total))]
        metric.count = [int(x) for x in sum(np.asarray(metric.count))]
    elif isinstance(metric, Precision):
        metric.tp = int(sum(np.asarray(metric.tp)))
        metric.fp = int(sum(np.asarray(metric.fp)))
    elif isinstance(metric, Recall):
        metric.tp = int(sum(np.asarray(metric.tp)))
        metric.fn = int(sum(np.asarray(metric.fn)))
    elif isinstance(metric, Auc):
        metric._stat_pos = sum(metric._stat_pos).astype(np.int64)
        metric._stat_neg = sum(metric._stat_neg).astype(np.int64)
    else:
        raise TypeError(f"unsupported metric {type(metric).__name__}")
    return metric.accumulate()
