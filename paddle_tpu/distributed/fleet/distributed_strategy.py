"""DistributedStrategy (reference: python/paddle/distributed/fleet/base/
distributed_strategy.py + framework/distributed_strategy.proto:122).

The reference stores this as protobuf; here a typed config tree (SURVEY §5
config translation). Every strategy bit of the reference is represented;
bits that are GPU-workarounds (fuse_grad_size_in_MB, nccl_comm_num…) are
accepted and recorded but are no-ops under XLA (documented per-field).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class RecomputeConfig:
    checkpoints: List[str] = field(default_factory=list)
    enable_offload: bool = False
    checkpoint_shape: List[int] = field(default_factory=list)


@dataclass
class AMPConfig:
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = True
    custom_white_list: List[str] = field(default_factory=list)
    custom_black_list: List[str] = field(default_factory=list)
    custom_black_varnames: List[str] = field(default_factory=list)
    use_pure_fp16: bool = False
    use_fp16_guard: bool = True
    dtype: str = "bfloat16"  # TPU default; "float16" honored with scaling


@dataclass
class ShardingConfig:
    segment_broadcast_MB: float = 32.0
    hybrid_dp: bool = False
    sharding_degree: int = 1
    sharding_stage: int = 2          # 1/2/3 (stage-3 is new vs reference)
    offload: bool = False


@dataclass
class PipelineConfig:
    micro_batch: int = 1
    accumulate_steps: int = 1
    schedule: str = "1F1B"   # improves on reference F-then-B


@dataclass
class TensorParallelConfig:
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


@dataclass
class HybridConfig:
    dp_degree: int = -1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sp_degree: int = 1   # sequence/context parallel (beyond reference)
    ep_degree: int = 1   # expert parallel (beyond reference)


@dataclass
class DGCConfig:
    rampup_begin_step: int = 0
    rampup_step: int = 1
    sparsity: List[float] = field(default_factory=lambda: [0.999])


@dataclass
class LarsConfig:
    lars_coeff: float = 0.001
    lars_weight_decay: float = 0.0005
    epsilon: float = 0.0
    exclude_from_weight_decay: List[str] = field(default_factory=list)


@dataclass
class LambConfig:
    lamb_weight_decay: float = 0.01
    exclude_from_weight_decay: List[str] = field(default_factory=list)


@dataclass
class LocalSGDConfig:
    k_steps: int = 1
    begin_step: int = 1


@dataclass
class AdaptiveLocalSGDConfig:
    init_k_steps: int = 1
    begin_step: int = 1


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class AsyncConfig:
    k_steps: int = -1
    max_merge_var_num: int = 1
    send_queue_size: int = 16
    independent_recv_thread: bool = False
    thread_pool_size: int = 1
    send_wait_times: int = 1
    runtime_split_send_recv: bool = False


class DistributedStrategy:
    def __init__(self):
        # feature switches (reference proto fields)
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.amp = False
        self.amp_configs = AMPConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.tensor_parallel = False
        self.tensor_parallel_configs = TensorParallelConfig()
        self.hybrid_configs = HybridConfig()
        self.dgc = False
        self.dgc_configs = DGCConfig()
        self.lars = False
        self.lars_configs = LarsConfig()
        self.lamb = False
        self.lamb_configs = LambConfig()
        self.localsgd = False
        self.localsgd_configs = LocalSGDConfig()
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = AdaptiveLocalSGDConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = GradientMergeConfig()
        self.fp16_allreduce = False      # bf16 collectives are the default
        self.a_sync = False              # PS async — out of TPU scope
        self.a_sync_configs = AsyncConfig()
        self.elastic = False
        self.auto = False
        # GPU-era execution knobs: accepted, no-op under XLA
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 1
        self.fuse_grad_size_in_MB = 32
        self.fuse_grad_size_in_TFLOPS = 50
        self.fuse_all_reduce_ops = True
        self.sync_nccl_allreduce = True
        self.sync_batch_norm = False
        self.find_unused_parameters = False
        self.last_comm_group_size_MB = 1
        self.cudnn_exhaustive_search = False
        self.conv_workspace_size_limit = 512
        self.cudnn_batchnorm_spatial_persistent = False
        # remat policy (TPU-native extension)
        self.recompute_granularity = "full"  # full | selective

    def _config(self, attr, configs: Dict[str, Any]):
        obj = getattr(self, attr)
        for k, v in configs.items():
            if hasattr(obj, k):
                setattr(obj, k, v)
        return obj

    # dict-style setters like the reference python wrapper
    def __setattr__(self, key, value):
        if key.endswith("_configs") and isinstance(value, dict):
            self._config(key, value)
        else:
            object.__setattr__(self, key, value)

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
