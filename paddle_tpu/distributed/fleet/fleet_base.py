"""Fleet facade (reference: python/paddle/distributed/fleet/base/
fleet_base.py — Fleet:63, init:130, distributed_optimizer:598,
distributed_model:643, minimize:1070).

The reference's meta-optimizer chain rewrites per-rank programs; here
`distributed_optimizer` + `distributed_model` configure ONE SPMD program
(strategy → mesh axes + shardings + remat + amp), compiled by
paddle_tpu.distributed.strategy_compiler (SURVEY §7 translation).
"""
from __future__ import annotations

import os
from typing import Optional

from ...optimizer.optimizer import Optimizer
from ..env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from .distributed_strategy import DistributedStrategy


class _RoleMaker:
    """reference: fleet/base/role_maker.py PaddleCloudRoleMaker — topology
    from env vars; rendezvous is the jax coordination service."""

    def __init__(self, is_collective=True):
        self.is_collective = is_collective

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return get_rank() == 0


class Fleet:
    def __init__(self):
        self._role_maker: Optional[_RoleMaker] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._compiled_step = None

    def init(self, role_maker=None, is_collective=True, strategy=None):
        init_parallel_env()
        self._role_maker = role_maker or _RoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        return self

    @property
    def _final_strategy(self):
        return self._strategy

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_endpoints(self, to_string=False):
        eps = ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    def distributed_optimizer(self, optimizer: Optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        return DistributedOptimizer(optimizer, self._strategy, self)

    def distributed_model(self, model):
        """Dygraph DP wrapper (reference: fleet_base.py:643 →
        paddle.DataParallel)."""
        from ..parallel import DataParallel

        self._model = model
        return DataParallel(model)

    # checkpoint delegation (reference fleet_base.py:518-550 — fleet
    # delegates sharded save to the runtime; here the runtime is
    # distributed.checkpoint: per-mesh-shard async files)
    def save_persistables(self, exe=None, dirname=None, main_program=None,
                          mode=0, trainer=None, model=None, optimizer=None,
                          step=0):
        """Save training persistables (params + opt state).

        trainer: a hybrid trainer exposing device_state() → sharded async
        checkpoint keyed by mesh shard. model/optimizer: eager state_dict
        save (rank 0 writes; other ranks no-op, matching the reference's
        should_save_model gating).
        """
        if dirname is None:
            dirname = exe if isinstance(exe, str) else None
        if dirname is None:
            raise ValueError("save_persistables needs dirname")
        if trainer is not None and hasattr(trainer, "device_state"):
            from .. import checkpoint as dck

            h = dck.save(dirname, trainer.device_state(), step=step,
                         meta={"step": step}, async_=False)
            return h.directory
        model = model or getattr(self, "_model", None)
        if model is None:
            raise ValueError(
                "save_persistables needs trainer= or model= on the TPU "
                "stack (no global static program exists)")
        if self.is_first_worker():
            from ...framework import io as fio

            state = {"model": model.state_dict()}
            if optimizer is not None:
                state["optimizer"] = optimizer.state_dict()
            fio.save(state, os.path.join(dirname, "persistables.pdparams"))
        return dirname

    def stop_worker(self):
        pass


class DistributedOptimizer:
    """reference: fleet_base.py distributed_optimizer return value. Applies
    the strategy at minimize/step time: lars/lamb swap, gradient merge,
    localsgd — eager semantics; for the compiled hybrid-parallel path use
    distributed.strategy_compiler.compile_train_step."""

    def __init__(self, inner_opt: Optimizer, strategy: DistributedStrategy,
                 fleet_obj: Fleet):
        self.inner_opt = self._maybe_swap(inner_opt, strategy)
        self.user_defined_strategy = strategy
        self._fleet = fleet_obj
        self._merge_count = 0

    @staticmethod
    def _maybe_swap(opt, strategy):
        """LARS/LAMB meta-optimizers (reference: meta_optimizers/
        lars_optimizer.py, lamb_optimizer.py) — swap the update rule."""
        from ...optimizer import Lamb, Lars, Momentum

        if strategy and strategy.lars and isinstance(opt, Momentum):
            c = strategy.lars_configs
            return Lars(opt._learning_rate, opt._momentum,
                        c.lars_coeff, c.lars_weight_decay,
                        parameters=opt._parameter_list,
                        grad_clip=opt._grad_clip, epsilon=c.epsilon)
        if strategy and strategy.lamb:
            c = strategy.lamb_configs
            return Lamb(opt._learning_rate,
                        lamb_weight_decay=c.lamb_weight_decay,
                        parameters=opt._parameter_list,
                        grad_clip=opt._grad_clip)
        return opt

    def __getattr__(self, name):
        return getattr(self.inner_opt, name)

    def step(self):
        strategy = self.user_defined_strategy
        if strategy and strategy.gradient_merge:
            k = strategy.gradient_merge_configs.k_steps
            self._merge_count += 1
            if self._merge_count % k != 0:
                return  # accumulate only (grads keep summing into .grad)
            if strategy.gradient_merge_configs.avg:
                for p in self.inner_opt._parameter_list or []:
                    if p.grad is not None:
                        p.grad._value = p.grad._value / k
        # LocalSGD (reference: meta_optimizers/localsgd_optimizer.py):
        # SKIP the per-step grad sync; every k steps average the PARAMS
        # across workers instead (one fused allreduce). adaptive variant
        # grows k as the lr decays (k_t = round(init_k * sqrt(lr0/lr_t)),
        # the Adaptive Communication Strategy schedule the reference's
        # AdaptiveLocalSGDOptimizer implements).
        localsgd = strategy is not None and (strategy.localsgd or
                                             strategy.adaptive_localsgd)
        self._local_step = getattr(self, "_local_step", 0) + 1
        if localsgd:
            begin = (strategy.adaptive_localsgd_configs.begin_step
                     if strategy.adaptive_localsgd
                     else strategy.localsgd_configs.begin_step)
            # before begin_step LocalSGD is plain synchronous SGD
            # (reference localsgd_optimizer.py: grads allreduce every
            # step until begin_step, then local steps start)
            local_phase = self._local_step >= begin
        else:
            local_phase = False
        # data-parallel grad sync across processes (dygraph DDP semantics —
        # reference: imperative Reducer). Inside pjit this is XLA's psum.
        if get_world_size() > 1 and not local_phase:
            from ..collective import all_reduce

            n = get_world_size()
            for p in self.inner_opt._parameter_list or []:
                if p.grad is not None:
                    all_reduce(p.grad)
                    p.grad._value = p.grad._value / n
        self.inner_opt.step()
        if local_phase and get_world_size() > 1:
            if strategy.adaptive_localsgd:
                cfg = strategy.adaptive_localsgd_configs
                lr0 = getattr(self, "_localsgd_lr0", None)
                if lr0 is None:
                    lr0 = self._localsgd_lr0 = float(
                        self.inner_opt.get_lr())
                lr = max(float(self.inner_opt.get_lr()), 1e-12)
                k = max(1, int(round(cfg.init_k_steps *
                                     (lr0 / lr) ** 0.5)))
            else:
                k = max(1, strategy.localsgd_configs.k_steps)
            # count steps SINCE THE LAST SYNC (a time-varying adaptive k
            # gated on a global step modulo would fire erratically)
            self._since_sync = getattr(self, "_since_sync", 0) + 1
            if self._since_sync >= k:
                self._average_parameters()
                self._since_sync = 0

    def _average_parameters(self):
        """Fused-bucket allreduce-average of the PARAM VALUES (the
        LocalSGD sync point; reference inserts c_allreduce on params,
        localsgd_optimizer.py)."""
        import jax.numpy as jnp

        from ...framework.tensor import Tensor
        from ..collective import all_reduce

        params = [p for p in (self.inner_opt._parameter_list or [])
                  if p is not None]
        if not params:
            return
        n = get_world_size()
        flats = [jnp.ravel(p._value).astype(jnp.float32) for p in params]
        sizes = [int(f.size) for f in flats]
        bucket = Tensor(jnp.concatenate(flats))
        all_reduce(bucket)
        merged = bucket._value / n
        off = 0
        for p, size in zip(params, sizes):
            p._value = merged[off:off + size].reshape(
                p._value.shape).astype(p._value.dtype)
            off += size

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.inner_opt.clear_grad()
        return [], []


fleet = Fleet()
