"""paddle.distributed.fleet equivalent."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import DistributedOptimizer, Fleet, fleet  # noqa: F401
from . import metrics  # noqa: F401

init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
worker_endpoints = fleet.worker_endpoints
barrier_worker = fleet.barrier_worker


class UserDefinedRoleMaker:
    def __init__(self, *args, **kwargs):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kwargs):
        self.is_collective = is_collective
