"""Model-agnostic hybrid-parallel trainer: dp × tp × pp × sp × ZeRO in ONE
pjit program.

The reference composes parallelism with a chain of meta-optimizers that
rewrite per-rank programs around USER model code (reference:
fleet/meta_optimizers/{sharding,pipeline,amp,recompute}_optimizer.py chained
by fleet/base/strategy_compiler.py; the pipeline splitter keys on per-op
device attributes, pipeline_optimizer.py:136) — model-agnostic by operating
on the program graph. Here the trainer is model-agnostic by a three-method
protocol any stacked-block model declares (models/gpt.py, models/bert.py):

  pipeline_stem(*batch)  -> activations       (embeddings)
  pipeline_blocks()      -> list of identical blocks (stackable params)
  pipeline_head(x, *batch) -> scalar loss     (norm + head + loss)

The trainer stacks block params to [pp, layers_per_stage, ...], shards the
stage axis over 'pp' (pipeline.py shard_map), scans/unrolls layers within a
stage, shards batch dim 0 over 'dp' (+ seq dim 1 over 'sp'), applies ZeRO
1/2/3 by adding a 'dp' axis to opt-state/param shardings, bf16-casts under
amp, and wraps blocks in jax.checkpoint under recompute — all in one jitted
step XLA can schedule globally.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import contextlib
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.place import target_platform as _target_platform
from ..framework.tensor import Tensor
from ..profiler import instrument as _pinstr
from ..profiler import recompile as _precomp
from ..profiler import trace as _ptrace
from ..profiler.metrics import registry as _preg
from ..static.functional import _swapped_state, state_tensors
from .fleet.distributed_strategy import DistributedStrategy
from .pipeline import pipeline_apply
from .strategy_compiler import (_add_axis, _local_check_shape,
                                build_mesh_from_strategy,
                                resolve_param_specs)


#: sentinel block_opt suffix carrying the fused ZeRO flat slabs (the
#: manual sharded-update path): one [dp*chunk] dp-sharded array per
#: optimizer-state key, riding the regular block_opt plumbing so
#: device_state / checkpointing / donation stay structure-agnostic
_ZERO_SLAB = "_zero_flat_"


def _check_protocol(model):
    for m in ("pipeline_stem", "pipeline_blocks", "pipeline_head"):
        if not hasattr(model, m):
            raise TypeError(
                f"{type(model).__name__} does not implement the pipeline "
                f"protocol ({m}); see distributed/hybrid.py docstring")


class HybridPipelineTrainer:
    """Compiled hybrid-parallel trainer for any pipeline-protocol model."""

    def __init__(self, model, optimizer,
                 strategy: Optional[DistributedStrategy] = None,
                 mesh: Optional[Mesh] = None, n_micro: Optional[int] = None,
                 v_virtual: Optional[int] = None,
                 remat_policy: Optional[str] = None,
                 param_dtype=None, moment_dtype=None,
                 offload_optimizer: bool = False,
                 offload_params: bool = False,
                 offload_depth: int = 2,
                 stream_layers: bool = False,
                 comp_resident: bool = True,
                 conservative_fetch: bool = False,
                 update_scan: bool = False,
                 unroll_layers: Optional[bool] = None,
                 free_eager: bool = False,
                 guard_bad_steps: bool = False,
                 dp_grad_comm: str = "f32",
                 dp_grad_block: int = 2048,
                 dp_param_comm: Optional[str] = None):
        """Memory knobs for billion-param single/few-chip configs
        (reference analogue: RecomputeConfig offload + ShardingConfig,
        distributed_strategy.proto:25-35):

        param_dtype:  storage dtype of the master params (default f32;
            'bfloat16' halves param memory — the update still computes
            in f32 and casts back).
        moment_dtype: storage dtype of optimizer moments (e.g.
            'bfloat16' halves AdamW state; update math stays f32).
        offload_optimizer: place optimizer state in pinned_host memory
            (the ZeRO-offload idea via XLA memory kinds). State streams
            host→HBM around the update each step — measured ~12 GB/s
            effective on a v5e host link, so this trades step time for
            HBM; use for models whose state cannot fit at any dtype.
        offload_params: ZeRO-Offload layout — the f32 master params live
            in pinned_host memory; each step streams them to HBM, casts
            to bf16 compute copies (grads are then bf16, halving grad
            HBM), and the f32 update streams master+moments through HBM
            per parameter group before writing back to host. Requires
            amp. This is the full-fidelity path for models whose f32
            master + f32 grads cannot fit HBM (1.3B+ on one 16 GB v5e).
        stream_layers: store host-offloaded state PER-LAYER and stream
            it through HBM behind a depth-``offload_depth``
            optimization_barrier chain (fetch layer k+1 ∥ f32 update
            layer k ∥ writeback layer k−1; the first fetches hide
            under forward/backward). With offload_params the forward
            runs on persistent bf16 compute copies, so per-step host
            traffic is one master read + one write. Bounds the HBM
            working set to ``offload_depth`` layers instead of a whole
            stacked group — the knob that fits 1.9B on one v5e
            (measured: 1.3B offload MFU 0.3955 → 0.4295;
            MEMO_SCALING_r05.md).
        comp_resident: (stream_layers) keep the bf16 compute copies as
            persistent trainer state (default). False re-streams the
            forward copies per-layer from host each step — a near-zero-
            HBM-argument program for toolchains that double-charge
            resident argument state at compile time.
        conservative_fetch: (stream_layers) additionally gate host
            fetches on the layer's gradient: no fetch overlaps
            forward/backward, trading the overlap for a smaller peak
            (the 1.9B fit knob).
        unroll_layers: unroll the per-stage layer loop. Default: unroll
            on TPU without remat (removes the scan's dynamic-slice
            bookkeeping), scan under remat — unrolling a rematerialized
            backward lets the latency-hiding scheduler hoist every
            layer's recomputation early, holding dozens of ffn
            intermediates live at once (measured 31% HBM fragmentation
            at 1.3B); the scan keeps layer backward strictly
            sequential so one layer's working set bounds live memory.
        free_eager: delete the eager model's device buffers after the
            trainer stacks/casts its own copies — at 1.3B the eager f32
            params are 5.3 GB of HBM that would sit dead next to the
            trainer's bf16 state. ``sync_to_layer`` restores them.

        Observability knobs (paddle_tpu.profiler; all zero-cost until
        ``profiler.enable()`` — the step reads one bool when disabled):

        profiler.enable(trace_dir=...): every ``step()`` then records an
            ``hybrid/h2d`` + ``hybrid/step`` host span (synced on the
            loss, so it measures execution, not dispatch), moves the
            ``train/steps`` / ``train/tokens`` counters and the
            ``hybrid/step_ms`` histogram, and tracks the device-memory
            high-water mark. An async-dispatch loop (elastic.py) sets
            ``profiled_step_sync = False`` to keep the profiled step
            from forcing the per-step sync it is hiding — the histogram
            is then honestly named ``hybrid/dispatch_ms`` and the
            deferred materializations record ``hybrid/sync_wait``;
            ``trace_dir`` additionally captures a
            TensorBoard-loadable XLA device trace. ``fwd/stem``,
            ``fwd/blocks``, ``fwd/head`` named scopes are baked into the
            compiled program, so XLA traces attribute device time per
            phase regardless of when profiling was switched on.
        profile_step_phases(*batch): fwd/bwd/optim/comm phase split as
            ``phase/*_ms`` gauges (two extra compiles; comm is modeled
            from collective bytes — see the method docstring).
        Resilience knob (paddle_tpu.resilience rides on it):

        guard_bad_steps: bake a finite check on the loss AND every
            clipped gradient leaf into the compiled step. A non-finite
            step keeps params and optimizer state bit-identical (the
            update is computed then deselected — momentum does not
            decay, weight decay does not apply), so one poisoned batch
            cannot touch the weights. ``last_step_ok`` reads the
            previous step's verdict (lazy device sync);
            ``inject_fault_scale(nan)`` poisons the NEXT step's loss —
            the deterministic NaN-gradient hook the chaos harness uses.
            Composes with ``offload_optimizer`` (the deselect runs on
            the device copies fetched for the update, so no state is
            double-streamed); unsupported with ``offload_params`` /
            ``stream_layers`` (the param select would force host-
            resident masters through HBM twice).

        retrace telemetry: every (re)trace of the step program is logged
            to ``profiler.retraces()`` with the triggering batch shapes;
            diagnostic lowerings (``aot_lower``/``memory_analysis``) are
            suppressed, so anything in the log is a silent recompile.
        profiler.summary()/export_chrome_trace(path): the collected
            picture — per-scope spans, counters, tokens/sec + steps/sec
            over the enabled window, phases, retraces."""
        _check_protocol(model)
        # MoE composes with pp: blocks return (h, aux) and pipeline_apply
        # carries the load-balance scalar across the schedule (stage_aux)
        cfg = getattr(model, "config", None)
        self.moe = bool(getattr(cfg, "moe_num_experts", 0))
        self.moe_aux_weight = float(getattr(cfg, "moe_aux_weight", 0.0))
        self.model = model
        self.optimizer = optimizer
        self.strategy = strategy or DistributedStrategy()
        self.mesh = mesh if mesh is not None else \
            build_mesh_from_strategy(self.strategy)
        self.pp = self.mesh.shape.get("pp", 1)
        self.n_micro = n_micro or max(
            self.strategy.pipeline_configs.accumulate_steps,
            self.strategy.pipeline_configs.micro_batch, self.pp)
        # interleaved/circular schedule degree (pipeline.py): v virtual
        # stages per device shrink the bubble v×
        self.v = v_virtual or getattr(self.strategy.pipeline_configs,
                                      "virtual_pipeline_degree", 1) or 1
        self.amp = self.strategy.amp
        self.remat = self.strategy.recompute
        # remat_policy "dots": selective remat — matmul outputs are saved,
        # elementwise/softmax recomputed. Most of full remat's memory win
        # at a fraction of its FLOP cost (full remat re-runs the matmuls
        # too, reference RecomputeOptimizer semantics).
        self.remat_policy = remat_policy
        self.zero = self.strategy.sharding_configs.sharding_stage \
            if self.strategy.sharding else 0
        self.param_dtype = jnp.dtype(param_dtype) if param_dtype else None
        self.moment_dtype = jnp.dtype(moment_dtype) if moment_dtype \
            else None
        self.offload_optimizer = offload_optimizer
        self.offload_params = offload_params
        # host↔HBM streaming pipeline depth: how many per-group f32
        # (p, m, v) working sets may be in flight at once. Deeper = more
        # copy/compute overlap, +1 group of transient HBM per step
        self.offload_depth = max(1, int(offload_depth))
        # update_scan: run the stacked-group optimizer update as a
        # lax.scan over layers — bounds f32 update transients to one
        # layer instead of a whole group. Opt-in: this environment's
        # remote compile helper SIGABRTs on the scan+offload composition
        # for some configs, so the default keeps the validated whole-
        # group update.
        self.update_scan = bool(update_scan)
        if offload_params and not self.amp:
            raise ValueError("offload_params requires strategy.amp (the "
                             "compute copies are bf16)")
        # stream_layers (MEMO_SCALING_r05 enabler, VERDICT r4 next #7):
        # host-offloaded state is stored PER-LAYER (lists, not one
        # stacked array) and the update python-unrolls over layers
        # behind a depth-``offload_depth`` optimization_barrier chain —
        # layer k+1's host→HBM fetch overlaps layer k's f32 update while
        # layer k−1's new master streams back. With offload_params the
        # forward also runs on PERSISTENT bf16 compute copies carried as
        # trainer state, eliminating the whole-model master fetch+cast
        # the whole-group path pays at the top of every step. Bounded
        # HBM: offload_depth layers' f32 working sets instead of a whole
        # stacked group (the 2.7B wall in MEMO_SCALING_r05.md).
        self.stream_layers = bool(stream_layers)
        # comp_resident (stream_layers + offload_params only): keep the
        # bf16 compute copies as persistent trainer state (fast path —
        # no forward-side master traffic). False streams the forward
        # copies per-layer from the host masters inside the program
        # instead: per-step host traffic grows by one master read, but
        # the program has ~zero HBM *arguments* — needed at 2.7B where
        # this toolchain's compile-time accounting charges resident
        # argument state on top of the (aliased) program requirement.
        self.comp_resident = bool(comp_resident)
        # conservative_fetch (stream_layers): additionally gate every
        # host fetch on the layer's GRADIENT, serializing fetches
        # behind backward. Lower peak HBM (no fetch overlaps fwd/bwd)
        # at the cost of the overlap — the knob that fits 1.9B on one
        # v5e, where the free schedule's ~1 GB of early-fetch
        # working set pushes past the 15.75 GB budget (measured:
        # 1.3B free 0.4295 @ 15.0 GB vs conservative 0.414 @ 4.9 GB).
        self.conservative_fetch = bool(conservative_fetch)
        if self.stream_layers:
            if not (offload_params or offload_optimizer):
                raise ValueError(
                    "stream_layers requires offload_params and/or "
                    "offload_optimizer (it schedules host streams)")
            if self.v != 1:
                raise ValueError(
                    "stream_layers supports v_virtual == 1 (per-layer "
                    "groups assume the [pp, lps, ...] stacking)")
        # PADDLE_TPU_FAKE_PINNED_HOST=1 (tests only): XLA:CPU has no
        # pinned_host memory space, so the virtual-mesh tests exercise
        # the full streaming program structure with both "spaces"
        # mapped to default device memory — placement differs, math and
        # schedule constraints are identical.
        if os.environ.get("PADDLE_TPU_FAKE_PINNED_HOST") == "1":
            self._host_kind, self._dev_kind = None, None
        else:
            self._host_kind, self._dev_kind = "pinned_host", "device"
        self.unroll_layers = unroll_layers
        # quantized DP-gradient sync (distributed/qcomm.py, ROADMAP 3b):
        # same semantics and constraints as the strategy compiler's knob
        # — per-shard local grads inside an all-manual shard_map over
        # 'dp', reduced by the EQuARX-style compressed ring. Pure-DP
        # only: the pipeline/tp/sp manual regions and ZeRO's grad
        # sharding don't compose with the wrap yet (residue).
        from .qcomm import validate_dp_grad_comm

        validate_dp_grad_comm(
            dp_grad_comm, self.mesh, zero_stage=self.zero,
            block=int(dp_grad_block),
            unsupported=(("offload_params (the host-streamed update "
                          "builders bypass the shard_map grad wrap)",
                          offload_params),
                         ("stream_layers", stream_layers)))
        self.dp_grad_comm = dp_grad_comm
        self.dp_grad_block = int(dp_grad_block)

        self._param_ns = lambda sp: NamedSharding(
            self.mesh, sp, memory_kind=self._host_kind) \
            if self.offload_params else NamedSharding(self.mesh, sp)

        blocks = list(model.pipeline_blocks())
        L = len(blocks)
        if L % (self.pp * self.v) != 0:
            raise ValueError(
                f"{L} blocks must be divisible by pp_degree×v_virtual="
                f"{self.pp}×{self.v}")
        self.lps = L // self.pp
        self.n_layers = L

        # --- split state: block params (stacked) vs the rest --------------
        pn, pt, bn, bt = state_tensors(model)
        name_by_id = {id(t): n for n, t in zip(pn, pt)}
        base_specs = resolve_param_specs(model, self.mesh, zero_stage=0)

        sfx0, t0 = state_tensors(blocks[0])[:2]
        self.block_suffixes = list(sfx0)
        self._blk0_tensors = list(t0)
        self._blk0_fullnames = [name_by_id[id(t)] for t in t0]
        per_block_tensors: List[List[Tensor]] = [t0]
        block_ids = set(id(t) for t in t0)
        for b in blocks[1:]:
            sfx_i, t_i = state_tensors(b)[:2]
            if list(sfx_i) != self.block_suffixes:
                raise ValueError(
                    "pipeline blocks must have identical structure; "
                    f"{sfx_i} != {self.block_suffixes}")
            per_block_tensors.append(list(t_i))
            block_ids.update(id(t) for t in t_i)

        self.other_names = [n for n, t in zip(pn, pt)
                            if id(t) not in block_ids]
        name2t = dict(zip(pn, pt))
        self._name2tensor = name2t
        self._per_block_tensors = per_block_tensors

        # LazyGuard (framework/lazy.py) models: every param is a
        # ShapeDtypeStruct. The trainer then *plans* instead of allocating
        # — stack/cast/shard through jax.eval_shape, optimizer state via
        # eval_shape of _init_state, and step() is AOT-only
        # (lower/compile/memory_analysis). This is the 13B path: planning
        # a 156 GB-state model allocates nothing anywhere.
        from ..framework.lazy import is_abstract
        self.abstract = any(is_abstract(t) for t in pt)

        dp = self.mesh.shape.get("dp", 1)

        # ZeRO-1/2 manual weight-update sharding (ISSUE 19; Xu et al.
        # 2004.13336): on a pure-DP mesh, stages 1-2 run the update
        # inside the ONE dp shard_map — reduce-scatter grads to their
        # owner shard (quantized or f32 ring per dp_grad_comm),
        # optimizer update on only the owned flat slice (state at
        # shard shape: the memory win), all-gather updated params back
        # (dp_param_comm payload). Compositions the manual wrap does
        # not cover yet fall back to the GSPMD _add_axis spelling
        # below (same memory claim, implicit collectives): host
        # offload / layer streaming (their update builders bypass the
        # wrap), storage-dtype casts, update_scan, and abstract
        # (LazyGuard) planning.
        pure_dp = all(s == 1 for a, s in self.mesh.shape.items()
                      if a != "dp")
        self.zero_manual = bool(
            self.zero in (1, 2) and dp > 1 and pure_dp
            and not self.abstract
            and not (offload_optimizer or offload_params
                     or stream_layers or update_scan)
            and self.param_dtype is None and self.moment_dtype is None)
        from . import qcomm as _qcomm
        if dp_param_comm is None:
            dp_param_comm = "bf16" if (self.zero_manual
                                       and dp_grad_comm == "int8") \
                else "f32"
        _qcomm.validate_dp_param_comm(dp_param_comm, self.zero_manual)
        self.dp_param_comm = dp_param_comm
        if self.zero_manual:
            gclip = optimizer._grad_clip
            from ..nn import ClipGradByGlobalNorm
            if gclip is not None and not isinstance(gclip,
                                                    ClipGradByGlobalNorm):
                raise NotImplementedError(
                    "ZeRO sharded update supports grad clipping only "
                    "by global norm (per-leaf clips need the full "
                    "gradient on every shard); got "
                    f"{type(gclip).__name__}")

        # stacked block params: [pp, lps, ...] (GPipe) or
        # [pp, v, lps/v, ...] (interleaved: stage s circuit c owns layers
        # (c·pp + s)·lps_v .. +lps_v — the circular assignment)
        self.block_vals: Dict[str, jax.Array] = {}
        self.block_specs: Dict[str, P] = {}
        # stream_layers: per-layer piece specs [pp, ...] and, with
        # offload_params, persistent bf16 compute copies (trainer state)
        self.block_layer_specs: Dict[str, P] = {}
        self.block_comp: Dict[str, jax.Array] = {}
        self.other_comp: List[jax.Array] = []
        for j, sfx in enumerate(self.block_suffixes):
            base = per_block_tensors[0][j]._value
            if self.v == 1:
                full_shape = (self.pp, self.lps) + tuple(base.shape)
                extra = (None,)
            else:
                lps_v = self.lps // self.v
                full_shape = (self.pp, self.v, lps_v) + tuple(base.shape)
                extra = (None, None)
            spec0 = base_specs[self._blk0_fullnames[j]]
            pp_ax = "pp" if "pp" in self.mesh.axis_names else None
            spec = P(pp_ax, *extra, *spec0)
            if self.zero >= 3:
                shape = _local_check_shape(full_shape, spec, self.mesh)
                spec = _add_axis(spec, len(full_shape), shape, "dp", dp)
            self.block_specs[sfx] = spec
            dt = base.dtype
            if self.param_dtype is not None and \
                    jnp.issubdtype(dt, jnp.floating):
                dt = self.param_dtype
            if self.stream_layers:
                lspec = P(pp_ax, *spec0)
                pshape = (self.pp,) + tuple(base.shape)
                if self.zero >= 3:
                    lshape = _local_check_shape(pshape, lspec, self.mesh)
                    lspec = _add_axis(lspec, len(pshape), lshape, "dp", dp)
                self.block_layer_specs[sfx] = lspec
            if self.stream_layers and self.offload_params:
                # per-layer host masters + one resident bf16 compute
                # stack. The full f32 stack is never materialized on
                # device (at 2.7B it would not fit next to the eager
                # params), and eager buffers are freed suffix-by-suffix
                # so the init peak declines as the comp copies grow.
                fl = jnp.issubdtype(dt, jnp.floating)
                cdt = jnp.bfloat16 if fl else dt
                lns = self._param_ns(self.block_layer_specs[sfx])
                pshape = (self.pp,) + tuple(base.shape)
                if self.abstract:
                    self.block_vals[sfx] = [
                        jax.ShapeDtypeStruct(pshape, dt, sharding=lns)
                        for _ in range(self.lps)]
                    if self.comp_resident:
                        self.block_comp[sfx] = jax.ShapeDtypeStruct(
                            full_shape, cdt,
                            sharding=NamedSharding(self.mesh, spec))
                else:
                    pieces, comp_pieces = [], []
                    for i in range(self.lps):
                        vals = [per_block_tensors[s * self.lps + i][j]
                                ._value for s in range(self.pp)]
                        piece = jnp.stack(vals, 0)
                        if dt != piece.dtype:
                            piece = piece.astype(dt)
                        pieces.append(jax.device_put(piece, lns))
                        if self.comp_resident:
                            comp_pieces.append(piece.astype(cdt))
                    self.block_vals[sfx] = pieces
                    if self.comp_resident:
                        self.block_comp[sfx] = jax.device_put(
                            jnp.stack(comp_pieces, 1),
                            NamedSharding(self.mesh, spec))
                    if free_eager:
                        for i in range(L):
                            t = per_block_tensors[i][j]
                            if t._value is not None:
                                t._value.delete()
                                t._value = None
                continue
            if self.abstract:
                stacked = jax.ShapeDtypeStruct(full_shape, base.dtype)
            else:
                per_layer = [per_block_tensors[i][j]._value
                             for i in range(L)]
                stacked = jnp.stack(per_layer, 0)
                if self.v == 1:
                    stacked = stacked.reshape(full_shape)
                else:
                    stacked = stacked.reshape(
                        (self.v, self.pp, lps_v) + per_layer[0].shape)
                    stacked = jnp.swapaxes(stacked, 0, 1)  # [pp,v,lps_v,...]
            if self.abstract:
                self.block_vals[sfx] = jax.ShapeDtypeStruct(
                    full_shape, dt, sharding=self._param_ns(spec))
            else:
                if dt != stacked.dtype:
                    stacked = stacked.astype(dt)
                self.block_vals[sfx] = jax.device_put(
                    stacked, self._param_ns(spec))

        self.other_vals: List[jax.Array] = []
        self.other_specs: List[P] = []
        for n in self.other_names:
            spec = base_specs[n]
            t = name2t[n]
            if self.zero >= 3:
                shape = _local_check_shape(t._value.shape, spec, self.mesh)
                spec = _add_axis(spec, t._value.ndim, shape, "dp", dp)
            self.other_specs.append(spec)
            v = t._value
            dt = v.dtype
            if self.param_dtype is not None and \
                    jnp.issubdtype(dt, jnp.floating):
                dt = self.param_dtype
            stream_comp = self.stream_layers and self.offload_params \
                and self.comp_resident
            if stream_comp:
                cdt = jnp.bfloat16 if jnp.issubdtype(dt, jnp.floating) \
                    else dt
            if self.abstract:
                self.other_vals.append(jax.ShapeDtypeStruct(
                    tuple(v.shape), dt, sharding=self._param_ns(spec)))
                if stream_comp:
                    self.other_comp.append(jax.ShapeDtypeStruct(
                        tuple(v.shape), cdt,
                        sharding=NamedSharding(self.mesh, spec)))
            else:
                if dt != v.dtype:
                    v = v.astype(dt)
                if stream_comp:
                    self.other_comp.append(jax.device_put(
                        v.astype(cdt), NamedSharding(self.mesh, spec)))
                self.other_vals.append(jax.device_put(
                    v, self._param_ns(spec)))

        # --- optimizer state ----------------------------------------------
        def opt_state_spec(spec, shape, ndim):
            if self.zero >= 1:
                local = _local_check_shape(shape, spec, self.mesh)
                return _add_axis(spec, ndim, local, "dp", dp)
            return spec

        class _FakeParam:
            def __init__(self, v):
                self._value = v

        def cast_state(s):
            if self.moment_dtype is None:
                return s
            return {k: v.astype(self.moment_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in s.items()}

        self._opt_ns = lambda sp: NamedSharding(
            self.mesh, sp, memory_kind=self._host_kind) \
            if self.offload_optimizer else NamedSharding(self.mesh, sp)

        def init_opt_state(v, sp):
            """Optimizer state for one (stacked) param: real arrays
            normally; shape-only (eval_shape of _init_state) in abstract
            mode, with the moment-dtype cast applied to the metadata."""
            if not self.abstract:
                s = cast_state(optimizer._init_state(_FakeParam(v)))
                return jax.device_put(s, {k: self._opt_ns(sp) for k in s})
            s = jax.eval_shape(
                lambda vv: optimizer._init_state(_FakeParam(vv)),
                jax.ShapeDtypeStruct(v.shape, v.dtype))
            out = {}
            for k, sd in s.items():
                dt = sd.dtype
                if self.moment_dtype is not None and \
                        jnp.issubdtype(dt, jnp.floating):
                    dt = self.moment_dtype
                out[k] = jax.ShapeDtypeStruct(
                    tuple(sd.shape), dt, sharding=self._opt_ns(sp))
            return out

        self.block_opt: Dict[str, dict] = {}
        self.block_opt_specs: Dict[str, dict] = {}
        self.other_opt: List[dict] = []
        self.other_opt_specs: List[dict] = []
        if self.zero_manual:
            # ONE fused flat slab per optimizer-state key, dp-sharded
            # [dp*chunk] (plus the f32 master param copy when the param
            # all-gather is compressed — bf16 round-trip rounding would
            # swallow small updates without it). It rides the regular
            # block_opt plumbing under a sentinel suffix so device
            # state / checkpointing / donation stay structure-agnostic.
            leaves = jax.tree_util.tree_leaves(
                (self.block_vals, self.other_vals))
            sizes = [int(np.prod(v.shape)) for v in leaves]
            self._zero_sizes = sizes
            self._zero_chunk = _qcomm.zero_chunk_len(
                sum(sizes), dp, self.dp_grad_block)
            slab = dp * self._zero_chunk
            st = optimizer._init_state(
                _FakeParam(jnp.zeros((slab,), jnp.float32)))
            if self.dp_param_comm != "f32":
                flat = np.concatenate(
                    [np.asarray(v, np.float32).reshape(-1)
                     for v in leaves]) if leaves \
                    else np.zeros(0, np.float32)
                st["master"] = jnp.asarray(
                    np.pad(flat, (0, slab - flat.size)))
            dp_sh = NamedSharding(self.mesh, P("dp"))
            self.block_opt[_ZERO_SLAB] = {
                k: jax.device_put(v, dp_sh) for k, v in st.items()}
            self.block_opt_specs[_ZERO_SLAB] = {k: P("dp") for k in st}
        for sfx, v in (() if self.zero_manual
                       else self.block_vals.items()):
            if self.stream_layers and self.offload_optimizer:
                # per-layer host-resident optimizer state (lists of
                # dicts, parallel to the per-layer masters)
                if isinstance(v, list):
                    pav = jax.ShapeDtypeStruct(tuple(v[0].shape),
                                               v[0].dtype)
                else:
                    pav = jax.ShapeDtypeStruct(
                        (v.shape[0],) + tuple(v.shape[2:]), v.dtype)
                sp = opt_state_spec(self.block_layer_specs[sfx],
                                    pav.shape, len(pav.shape))
                lst = [init_opt_state(pav, sp) for _ in range(self.lps)]
                self.block_opt[sfx] = lst
                self.block_opt_specs[sfx] = {k: sp for k in lst[0]}
                continue
            if isinstance(v, list):
                # stream_layers+offload_params with RESIDENT moments:
                # stacked state from the stacked master aval
                # (_init_state is shape-only; no f32 stack materializes)
                v = jax.ShapeDtypeStruct(
                    (self.pp, self.lps) + tuple(v[0].shape[1:]),
                    v[0].dtype)
            sp = opt_state_spec(self.block_specs[sfx], v.shape, v.ndim)
            s = init_opt_state(v, sp)
            self.block_opt[sfx] = s
            self.block_opt_specs[sfx] = {k: sp for k in s}
        for n, v, spec in (() if self.zero_manual else zip(
                self.other_names, self.other_vals, self.other_specs)):
            sp = opt_state_spec(spec, v.shape, v.ndim)
            s = init_opt_state(v, sp)
            self.other_opt.append(s)
            self.other_opt_specs.append({k: sp for k in s})

        if free_eager and not self.abstract:
            # device_put may return a NEW Array sharing the SAME buffer
            # when dtype+sharding are unchanged, so aliasing cannot be
            # detected by identity. Delete only buffers that are
            # provably fresh copies: per-layer block params (jnp.stack
            # always materializes a new stacked buffer) and other params
            # whose dtype cast forced a copy. An uncast "other" param
            # keeps sharing its buffer with the trainer — dropping the
            # eager reference alone still releases nothing extra, and
            # deleting would kill the trainer's own state.
            for ts in per_block_tensors:
                for t in ts:
                    if t._value is not None:   # stream path freed it
                        t._value.delete()
                        t._value = None
            for n, v in zip(self.other_names, self.other_vals):
                t = name2t[n]
                if t._value.dtype != v.dtype:
                    t._value.delete()
                t._value = None

        self.guard_bad_steps = bool(guard_bad_steps)
        if self.guard_bad_steps and (offload_params or stream_layers):
            raise ValueError(
                "guard_bad_steps is not supported with offload_params/"
                "stream_layers yet (the bad-step select would stream "
                "host-resident state through HBM a second time); "
                "offload_optimizer alone composes — its deselect runs "
                "on the device copies already fetched for the update")
        # device-side verdict of the last guarded step (None before the
        # first step / when unguarded); _fault_scale poisons exactly one
        # upcoming step's loss (chaos harness hook)
        self._last_ok_dev = None
        self._fault_scale: Optional[float] = None

        self._step = 0
        self._n_batch_args: Optional[int] = None
        self._step_fn = None
        # recompilation telemetry: every (re)trace of this trainer's step
        # program is reported to profiler.recompile under this site
        self._prof_site = _precomp.unique_site("hybrid.step")

    # ---------------------------------------------------------------------
    def _forward_loss(self, block_params, other_params, batch, key):
        model = self.model
        from ..core import rng as rng_mod

        if self.amp:
            castf = lambda v: v.astype(jnp.bfloat16) if \
                jnp.issubdtype(v.dtype, jnp.floating) else v
        else:
            castf = lambda v: v
        other_cast = [castf(v) for v in other_params]
        block_cast = {k: castf(v) for k, v in block_params.items()}

        other_tensors = [self._name2tensor[n] for n in self.other_names]
        blk0_tensors = self._blk0_tensors
        sp = self.mesh.shape.get("sp", 1)

        def seq_constraint(h):
            """Keep activations sequence-sharded between ring attentions.
            Skipped for bf16 on XLA:CPU (tests): resharding constraints on
            bf16 trip a CPU-backend crash; TPU is unaffected."""
            if sp > 1 and not (_target_platform() == "cpu"
                               and h.dtype == jnp.bfloat16):
                return jax.lax.with_sharding_constraint(
                    h, NamedSharding(self.mesh, P("dp", "sp", None)))
            return h

        from . import context as dctx
        manual_sp = sp > 1 and self.pp > 1
        block0 = model.pipeline_blocks()[0]

        moe = self.moe
        aux_w = self.moe_aux_weight

        def block_apply(stage_local, x):
            """Apply one stage's lps blocks (lax.scan over layers).
            MoE models: returns (out, weighted aux-loss sum of the
            stage's blocks) — the pipeline's stage_aux contract."""
            # axes that stay GSPMD-auto inside the manual-pp region:
            # pallas kernels must nest a shard_map over them (Mosaic
            # cannot be auto-partitioned in a partially-manual region).
            # pp == 1 runs fully auto — no scope needed. On jax < 0.5
            # the pipeline shard_map is manual over EVERY axis
            # (pipeline.py legacy_all_manual), so there are no auto
            # axes to declare either.
            auto_axes = tuple(a for a in self.mesh.axis_names
                              if a != "pp" and not (manual_sp and a == "sp"))
            auto_scope = (
                (lambda: dctx.pipeline_auto_axes_scope(self.mesh,
                                                       auto_axes))
                if self.pp > 1 and hasattr(jax, "shard_map")
                else contextlib.nullcontext)

            def one_block(h, layer_params):
                vals = [layer_params[s] for s in self.block_suffixes]
                with _swapped_state(blk0_tensors, vals), auto_scope():
                    if manual_sp:
                        with dctx.manual_sequence_parallel_scope():
                            out = block0(Tensor(h))._value
                    else:
                        out = block0(Tensor(h))._value
                    aux = block0.mlp._aux._value if moe else None
                return (out, aux) if moe else out

            if self.remat:
                if self.remat_policy == "dots":
                    one_block = jax.checkpoint(
                        one_block,
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable)
                else:
                    one_block = jax.checkpoint(one_block)

            def body(carry, layer_params):
                if moe:
                    h, a = carry
                    out, aux = one_block(h, layer_params)
                    return (out, a + aux.astype(jnp.float32)), None
                return one_block(carry, layer_params), None

            init = (x, jnp.zeros((), jnp.float32)) if moe else x
            unroll = self.unroll_layers if self.unroll_layers is not None \
                else (_target_platform() != "cpu" and not self.remat)
            out, _ = jax.lax.scan(body, init, stage_local, unroll=unroll)
            if moe:
                h, a = out
                return h, a * aux_w
            return out

        batch_tensors = [Tensor(b) for b in batch]
        # loss-inside-pipeline: the head runs in the manual region and only
        # a SCALAR crosses 'pp' (vs the full activation buffer). Disabled
        # under manual sp (head must see the sp-sharded output) and under
        # CPU+amp (bf16 cotangent psum trips XLA:CPU). tp>1 is supported:
        # the vocab-sharded head's tp collectives ride GSPMD-auto inside
        # the manual-pp region like the blocks' do.
        import os
        # jax < 0.5: the legacy shard_map's partial-eval drops the scalar-
        # residual promotion for jax.checkpoint'ed bodies (the fused CE's
        # scalar scan carry trips `_SpecError` at transpose time), so the
        # head stays OUTSIDE the manual region there — the masked-psum
        # egress below is the numerically-identical fallback.
        head_inside = not manual_sp and self.pp > 1 and not (
            _target_platform() == "cpu" and self.amp) and \
            hasattr(jax, "shard_map") and \
            os.environ.get("PADDLE_TPU_HEAD_INSIDE", "1") != "0"
        with _swapped_state(other_tensors, other_cast), \
                dctx.sequence_parallel_scope(self.mesh):
            with rng_mod.key_scope(key):
                # fwd/* named scopes: pure op-name metadata traced into
                # the program, so XLA traces/HLO dumps attribute device
                # time to the phase (profiler/trace.py annotate)
                with _ptrace.annotate("fwd/stem"):
                    x = model.pipeline_stem(*batch_tensors)._value
                    x = seq_constraint(x)
                if head_inside:
                    # head params + batch enter the manual region as
                    # explicit inputs; blocks' swapped values are local
                    def head_fn(full, other_vals, batch_vals):
                        with _swapped_state(other_tensors,
                                            list(other_vals)), \
                                _ptrace.annotate("fwd/head"):
                            return model.pipeline_head(
                                Tensor(full),
                                *[Tensor(b) for b in batch_vals])._value
                    with _ptrace.annotate("fwd/blocks"):
                        loss_v = pipeline_apply(
                            self.mesh, block_apply, block_cast, x,
                            self.n_micro, v_virtual=self.v,
                            head_fn=head_fn,
                            head_args=(tuple(other_cast), tuple(batch)),
                            stage_aux=moe)
                    if moe:
                        loss_v, aux = loss_v
                        return (loss_v + aux).astype(jnp.float32)
                    return loss_v.astype(jnp.float32)
                with _ptrace.annotate("fwd/blocks"):
                    x = pipeline_apply(self.mesh, block_apply, block_cast,
                                       x, self.n_micro, v_virtual=self.v,
                                       sp_axis="sp" if manual_sp else None,
                                       stage_aux=moe)
                aux = None
                if moe:
                    x, aux = x
                with _ptrace.annotate("fwd/head"):
                    x = Tensor(seq_constraint(x))
                    loss = model.pipeline_head(x, *batch_tensors)
                    if aux is not None:
                        loss = loss + Tensor(aux)
        return loss._value.astype(jnp.float32)

    def _cast_back(self, np_, ns, store_p_dtype, store_s):
        """Shared storage-dtype rule for both update builders: the f32
        update result is stored back at the configured param/moment
        dtypes (param_dtype/moment_dtype knobs)."""
        if self.param_dtype is not None and \
                jnp.issubdtype(store_p_dtype, jnp.floating):
            np_ = np_.astype(store_p_dtype)
        if self.moment_dtype is not None:
            ns = {k: v.astype(store_s[k].dtype)
                  if jnp.issubdtype(v.dtype, jnp.floating) else v
                  for k, v in ns.items()}
        return np_, ns

    def _make_batch_spec(self):
        """Batch-arg sharding: dim 0 over dp, dim 1 over sp when present
        (shared by both step builders)."""
        sp = self.mesh.shape.get("sp", 1)

        def batch_spec(ndim):
            if ndim >= 2 and sp > 1:
                return P("dp", "sp")
            return P("dp") if ndim >= 1 else P()

        return batch_spec

    def _update_ctx(self):
        """Shared update-builder prologue: the per-parameter update fn,
        clip, and the per-suffix/per-other lr & decoupled-wd tables
        (used identically by _build and _build_stream)."""
        from .strategy_compiler import make_param_update

        opt = self.optimizer
        wd_other = tuple(opt._decoupled_wd(self._name2tensor[n])
                         for n in self.other_names)
        lr_other = tuple(
            self._name2tensor[n].optimize_attr.get("learning_rate", 1.0)
            for n in self.other_names)
        wd_block = {s: opt._decoupled_wd(t) for s, t in
                    zip(self.block_suffixes, self._blk0_tensors)}
        lr_block = {s: t.optimize_attr.get("learning_rate", 1.0)
                    for s, t in zip(self.block_suffixes,
                                    self._blk0_tensors)}
        return (make_param_update(opt), opt._grad_clip, wd_other,
                lr_other, wd_block, lr_block)

    def _build(self, n_batch_args: int):
        if self.stream_layers:
            return self._build_stream(n_batch_args)
        from .strategy_compiler import functional_clip

        upd, clip, wd_other, lr_other, wd_block, lr_block = \
            self._update_ctx()
        mesh = self.mesh

        offload = self.offload_optimizer
        mesh_ = self.mesh

        def fetch_state(s, spec):
            """Offload: stream host-resident state into HBM for the
            update (XLA inserts the copies; overlappable by the
            latency-hiding scheduler)."""
            if not offload:
                return s
            return {k: jax.device_put(
                v, NamedSharding(mesh_, spec[k],
                                 memory_kind=self._dev_kind))
                for k, v in s.items()}

        offload_p = self.offload_params

        # update_scan (opt-in): the f32 update math materializes f32
        # copies of a WHOLE stacked group (p, g, m, v — at 2.7B the
        # largest group is 0.84 B params ⇒ ~13 GB of f32 transients,
        # which cannot fit next to the resident bf16 state). Scanning
        # the update over the stacked layer dim bounds the f32 working
        # set to ONE layer; the math is elementwise per parameter so the
        # scan is exact.
        scan_update = self.update_scan

        def core_upd(p, g, s_dev, lr, step_no, plr, wd, store_p_dtype,
                     store_s):
            np_, ns = upd(p, g, s_dev, lr, step_no, plr=plr, wd=wd)
            return self._cast_back(np_, ns, store_p_dtype, store_s)

        def upd2(p, g, s, spec, lr, step_no, plr, wd, pspec=None,
                 stacked=False, ok=None):
            """Update in f32 math, store back at the configured dtypes
            (+ host placement handled by out_shardings when offloading).

            ``ok`` (guard_bad_steps): the step verdict. The bad-step
            deselect happens HERE, on the device-resident operands — the
            pre-update param ``p`` and the fetched ``s_dev`` — not on the
            host-resident inputs, so an offloaded optimizer state is
            never streamed through HBM a second time just to undo the
            update: the selected (old) values flow back to pinned_host
            through the same out_shardings the updated ones would."""
            if offload_p:
                p = jax.device_put(p, NamedSharding(
                    mesh_, pspec, memory_kind=self._dev_kind))
            s_dev = fetch_state(s, spec)

            def deselect(np_, ns):
                if ok is None:
                    return np_, ns
                np_ = jnp.where(ok, np_, p)
                ns = {k: jnp.where(ok, v, s_dev[k])
                      for k, v in ns.items()}
                return np_, ns

            if scan_update and stacked and p.ndim >= 3:
                lead = p.shape[0] * p.shape[1]
                pf = p.reshape((lead,) + p.shape[2:])
                gf = g.reshape((lead,) + g.shape[2:])
                sf = {k: v.reshape((lead,) + v.shape[2:])
                      for k, v in s_dev.items()}

                def body(carry, xs):
                    pi, gi, si = xs
                    npi, nsi = core_upd(pi, gi, si, lr, step_no, plr, wd,
                                        p.dtype, {k: s[k] for k in si})
                    return carry, (npi, nsi)

                _, (npf, nsf) = jax.lax.scan(body, 0, (pf, gf, sf))
                np_ = npf.reshape(p.shape)
                ns = {k: v.reshape(s_dev[k].shape)
                      for k, v in nsf.items()}
                return deselect(np_, ns)
            return deselect(
                *core_upd(p, g, s_dev, lr, step_no, plr, wd, p.dtype, s))

        guard = self.guard_bad_steps
        qcomm_dp = self.mesh.shape.get("dp", 1) \
            if self.dp_grad_comm == "int8" else 1
        zero_manual = self.zero_manual
        if zero_manual:
            from .strategy_compiler import _flat_knob, make_flat_update

            zdp = self.mesh.shape.get("dp", 1)
            flat_upd = make_flat_update(self.optimizer)
            clip_norm = float(clip.clip_norm) if clip is not None \
                else None
            slab = zdp * self._zero_chunk
            # knob vectors laid out like the fused param buffer: leaf
            # order is tree_flatten((block_vals, other_vals)) — sorted
            # block suffixes (jax dict order), then the other list
            bkeys = sorted(self.block_vals.keys())
            plr_knob = _flat_knob(
                [lr_block[s] for s in bkeys] + list(lr_other),
                self._zero_sizes, slab)
            wd_knob = _flat_knob(
                [wd_block[s] for s in bkeys] + list(wd_other),
                self._zero_sizes, slab)

        def step_fn(block_params, other_params, block_opt, other_opt,
                    batch, lr, step_no, key, *guard_args):
            # python side effect at the top of the traced body: runs once
            # per trace, so every cache miss (silent recompile) is logged
            # with the batch shapes that triggered it
            _precomp.mark_trace(self._prof_site, batch)
            fault = guard_args[0] if guard else None
            if offload_p:
                # stream masters to HBM and cast; grads flow to the bf16
                # compute copies (half the grad HBM of the f32 path)
                def dev_cast(v, spec):
                    v = jax.device_put(v, NamedSharding(
                        mesh_, spec, memory_kind=self._dev_kind))
                    return v.astype(jnp.bfloat16) \
                        if jnp.issubdtype(v.dtype, jnp.floating) else v
                bp_c = {k: dev_cast(v, self.block_specs[k])
                        for k, v in block_params.items()}
                op_c = [dev_cast(v, s) for v, s in
                        zip(other_params, self.other_specs)]
            else:
                bp_c, op_c = block_params, other_params

            def grads_of(bp, op, batch_, key_, fault_):
                def loss_of(bp_, op_):
                    l = self._forward_loss(bp_, op_, batch_, key_)
                    # fault is 1.0 in normal operation (exact IEEE
                    # noop); the chaos harness sets it to NaN for one
                    # step, which poisons the loss AND (through the
                    # cotangent) every gradient leaf — the guard below
                    # must catch all of it
                    return l * fault_ if guard else l

                return jax.value_and_grad(loss_of, argnums=(0, 1))(bp, op)

            if zero_manual:
                # ZeRO-1/2 sharded update: the ONE shared shard_map
                # wrap (qcomm.dp_zero_step) does per-shard local
                # grads, fused reduce-scatter (quantized or f32 ring
                # per dp_grad_comm), global-norm clip on the reduced
                # chunks, the guard verdict on the REDUCED shard grads
                # (pmin-agreed across the mesh — every shard takes the
                # identical keep/skip branch), the shard-local flat
                # optimizer update, and the param all-gather
                # (dp_param_comm payload). Replaces the per-suffix
                # upd2 loop below entirely.
                from . import qcomm as _zq

                def local(rep, params_, key_, batch_):
                    bp, op = params_
                    loss, grads = grads_of(bp, op, batch_, key_, rep)
                    return loss, (), grads

                ft = fault if guard else jnp.float32(1.0)
                res = _zq.dp_zero_step(
                    mesh, zdp, self.dp_grad_block, self.dp_grad_comm,
                    self.dp_param_comm, local, flat_upd, ft,
                    (block_params, other_params),
                    block_opt[_ZERO_SLAB], batch,
                    _zq.dp_batch_specs(batch, zdp), key, lr, step_no,
                    plr_knob, wd_knob, clip_norm=clip_norm,
                    guard=guard)
                if guard:
                    loss, _, (nb, no), new_flat, ok = res
                    return (loss, ok, nb, no, {_ZERO_SLAB: new_flat},
                            [])
                loss, _, (nb, no), new_flat = res
                return loss, nb, no, {_ZERO_SLAB: new_flat}, []

            if qcomm_dp > 1:
                # quantized DP-grad sync: per-shard local grads inside
                # the ONE shared all-manual shard_map wrap (qcomm.py),
                # reduced by the EQuARX-style compressed ring. pmean of
                # the per-shard mean losses == the global mean loss;
                # the quantized ring replaces the grads' pmean — the
                # only numeric difference vs the GSPMD path.
                from . import qcomm as _qcomm

                def local(rep, key_, batch_):
                    bp, op, ft = rep
                    loss, grads = grads_of(bp, op, batch_, key_, ft)
                    return loss, (), grads

                ft = fault if guard else jnp.float32(1.0)
                loss, _, (g_blk, g_oth) = \
                    _qcomm.dp_quantized_value_and_grads(
                        mesh, qcomm_dp, self.dp_grad_block, local,
                        (bp_c, op_c, ft), batch,
                        _qcomm.dp_batch_specs(batch, qcomm_dp), key)
            else:
                loss, (g_blk, g_oth) = grads_of(bp_c, op_c, batch, key,
                                                fault)
            g_blk, g_oth = functional_clip(clip, (g_blk, g_oth))

            ok = None
            if guard:
                # one scalar verdict for the whole step: loss and every
                # clipped grad leaf finite. isfinite-per-leaf (not a
                # squared global norm) so legitimately-huge-but-finite
                # grads cannot overflow the check itself. The deselect
                # itself happens inside upd2 on device-resident values
                # (see its docstring) — params AND optimizer state stay
                # bit-identical on a bad step (momentum does not decay,
                # weight decay does not apply).
                ok = jnp.isfinite(loss)
                for g_ in jax.tree_util.tree_leaves((g_blk, g_oth)):
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g_)))

            # offload_params: serialize the per-group host↔HBM update
            # streams (fetch k waits on update k-depth) — unconstrained,
            # the scheduler launches every group's copy-in during
            # backward and the transient f32 state OOMs; chained,
            # offload_depth groups' f32 (p, m, v) are in HBM at a time
            # and copy-in of group k overlaps update k-1 and copy-out of
            # group k-depth on the full-duplex link.
            chain = [loss] * self.offload_depth
            any_offload = offload_p or offload

            def barriered(p, g, s):
                # serialize per-group host fetches whenever ANY state is
                # host-resident — with only the optimizer offloaded the
                # unconstrained scheduler would fetch every group's
                # moments during backward and OOM on the f32 update
                # transients (hit at 2.7B moment-offload)
                if not any_offload:
                    return p, g, s
                (p, g, _), s = jax.lax.optimization_barrier(
                    ((p, g, chain.pop(0)), s))
                return p, g, s

            new_blk, new_blk_opt = {}, {}
            for sfx in block_params:
                p, g, s = barriered(block_params[sfx], g_blk[sfx],
                                    block_opt[sfx])
                np_, ns = upd2(p, g, s, self.block_opt_specs[sfx],
                               lr, step_no, lr_block[sfx], wd_block[sfx],
                               pspec=self.block_specs[sfx], stacked=True,
                               ok=ok)
                new_blk[sfx] = np_
                new_blk_opt[sfx] = ns
                if any_offload:
                    chain.append(np_)
            new_oth, new_oth_opt = [], []
            for p, g, s, sspec, pspec, plr, wd in zip(
                    other_params, g_oth, other_opt, self.other_opt_specs,
                    self.other_specs, lr_other, wd_other):
                p, g, s = barriered(p, g, s)
                np_, ns = upd2(p, g, s, sspec, lr, step_no, plr, wd,
                               pspec=pspec, ok=ok)
                new_oth.append(np_)
                new_oth_opt.append(ns)
                if any_offload:
                    chain.append(np_)
            if guard:
                return (loss, ok, new_blk, new_oth, new_blk_opt,
                        new_oth_opt)
            return loss, new_blk, new_oth, new_blk_opt, new_oth_opt

        ns = lambda spec: NamedSharding(mesh, spec)
        ons = self._opt_ns          # pinned_host when offloading
        pns = self._param_ns        # pinned_host when offload_params
        blk_sh = {k: pns(v) for k, v in self.block_specs.items()}
        oth_sh = [pns(s) for s in self.other_specs]
        blk_opt_sh = {k: {kk: ons(vv) for kk, vv in v.items()}
                      for k, v in self.block_opt_specs.items()}
        oth_opt_sh = [{kk: ons(vv) for kk, vv in d.items()}
                      for d in self.other_opt_specs]
        self._batch_spec = self._make_batch_spec()
        in_sh = (blk_sh, oth_sh, blk_opt_sh, oth_opt_sh,
                 None, None, None, None)
        out_sh = (ns(P()), blk_sh, oth_sh, blk_opt_sh, oth_opt_sh)
        if guard:
            in_sh = in_sh + (None,)                       # fault scalar
            out_sh = (ns(P()), ns(P())) + out_sh[1:]      # + ok verdict
        self._step_fn = jax.jit(
            step_fn, in_shardings=in_sh, out_shardings=out_sh,
            donate_argnums=(0, 1, 2, 3))
        self._n_batch_args = n_batch_args

    def _build_stream(self, n_batch_args: int):
        """stream_layers step: per-layer host↔HBM streaming update.

        One pjit program; ordering comes from a depth-``offload_depth``
        optimization_barrier chain seeded on the step counter, so the
        first ``depth`` layer fetches launch at program start and hide
        under forward/backward, after which fetch k waits on update
        k−depth (not on its writeback):

            fetch layer k+1 (host→HBM) ∥ f32 update layer k ∥
            writeback layer k−1 (HBM→host)

        With offload_params the forward/backward run on PERSISTENT bf16
        compute copies carried as trainer state and rebuilt by each
        update, so per-step host traffic is exactly one master read +
        one master write — the whole-group path's additional whole-
        model master fetch+cast at the top of every step is gone.
        Reference analogue: the staged ZeRO-Offload update
        (reference: python/paddle/incubate/optimizer/distributed_fused_lamb.py,
        paddle/fluid/operators/optimizers/distributed_fused_lamb_op.cc),
        scheduled here by XLA instead of CUDA streams."""
        from .strategy_compiler import functional_clip

        upd, clip, wd_other, lr_other, wd_block, lr_block = \
            self._update_ctx()
        mesh = self.mesh
        offload_p = self.offload_params
        offload_o = self.offload_optimizer
        depth = self.offload_depth
        devk = self._dev_kind
        lps = self.lps
        sfx_list = list(self.block_suffixes)

        def to_dev(v, spec):
            return jax.device_put(
                v, NamedSharding(mesh, spec, memory_kind=devk))

        def bf16_of(v):
            return v.astype(jnp.bfloat16) \
                if jnp.issubdtype(v.dtype, jnp.floating) else v

        def one_group(pm, g, s, gate, p_spec, s_specs, plr, wd, lr,
                      step_no):
            """Barrier-gated fetch → f32 update → storage-dtype cast for
            one parameter group (one layer's suffix, or one 'other').

            By default only the HOST-RESIDENT operands (pm, s) are tied
            to the gate: including g would chain the fetch to the
            gradient, which the layer-scan backward produces only at
            its end — serializing every fetch behind backward (the r4
            behavior this rework removes). g is device-resident and
            needs no gating; the update itself waits on it naturally.
            conservative_fetch opts back into the grad gate where the
            free schedule's early-fetch working set exceeds HBM."""
            if self.conservative_fetch:
                (pm, g, _), s = jax.lax.optimization_barrier(
                    ((pm, g, gate), s))
            else:
                (pm, _), s = jax.lax.optimization_barrier(
                    ((pm, gate), s))
            pm_d = to_dev(pm, p_spec) if offload_p and p_spec is not None \
                else pm
            s_d = {k: to_dev(v, s_specs[k]) for k, v in s.items()} \
                if offload_o and s_specs is not None else s
            np_, ns = upd(pm_d, g, s_d, lr, step_no, plr=plr, wd=wd)
            return self._cast_back(np_, ns, pm.dtype, s)

        comp_res = self.comp_resident

        def step_fn(blk_m, oth_m, blk_c, oth_c, blk_o, oth_o,
                    batch, lr, step_no, key):
            _precomp.mark_trace(self._prof_site, batch)
            if offload_p and not comp_res:
                # no persistent compute copies: stream the forward's
                # bf16 copies per-layer from the host masters, chained
                # so ≤depth f32 pieces are in flight (the zero-argument
                # layout — see comp_resident in __init__)
                fchain = [step_no] * depth
                bl = {s: [None] * lps for s in sfx_list}
                for i in range(lps):
                    gate = fchain.pop(0)
                    last = gate
                    for sfx in sfx_list:
                        (pm, _) = jax.lax.optimization_barrier(
                            (blk_m[sfx][i], gate))
                        c = bf16_of(to_dev(
                            pm, self.block_layer_specs[sfx]))
                        bl[sfx][i] = c
                        last = c
                    fchain.append(last)
                bp = {s: jax.lax.with_sharding_constraint(
                    jnp.stack(bl[s], 1),
                    NamedSharding(mesh, self.block_specs[s]))
                    for s in sfx_list}
                op = [bf16_of(to_dev(oth_m[idx], self.other_specs[idx]))
                      for idx in range(len(oth_m))]
            elif offload_p:
                bp, op = blk_c, oth_c
            else:
                bp, op = blk_m, oth_m

            def loss_of(b, o):
                return self._forward_loss(b, o, batch, key)

            loss, (g_blk, g_oth) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(bp, op)
            g_blk, g_oth = functional_clip(clip, (g_blk, g_oth))

            chain = [step_no] * depth
            new_m = {s: [None] * lps for s in sfx_list}
            new_c = {s: [None] * lps for s in sfx_list}
            new_o = {s: [None] * lps for s in sfx_list}
            for i in range(lps):
                gate = chain.pop(0)
                last = gate
                for sfx in sfx_list:
                    if offload_p:
                        pm = blk_m[sfx][i]
                    else:
                        pm = jax.lax.index_in_dim(blk_m[sfx], i, 1,
                                                  keepdims=False)
                    g = jax.lax.index_in_dim(g_blk[sfx], i, 1,
                                             keepdims=False)
                    if offload_o:
                        s = blk_o[sfx][i]
                    else:
                        s = {k: jax.lax.index_in_dim(v, i, 1,
                                                     keepdims=False)
                             for k, v in blk_o[sfx].items()}
                    np_, ns = one_group(
                        pm, g, s, gate, self.block_layer_specs[sfx],
                        self.block_opt_specs[sfx] if offload_o else None,
                        lr_block[sfx], wd_block[sfx], lr, step_no)
                    new_m[sfx][i] = np_
                    if offload_p and comp_res:
                        new_c[sfx][i] = bf16_of(np_)
                    new_o[sfx][i] = ns
                    last = np_
                chain.append(last)

            new_oth_m, new_oth_c, new_oth_o = [], [], []
            for idx in range(len(oth_m)):
                gate = chain.pop(0)
                np_, ns = one_group(
                    oth_m[idx], g_oth[idx], oth_o[idx], gate,
                    self.other_specs[idx],
                    self.other_opt_specs[idx] if offload_o else None,
                    lr_other[idx], wd_other[idx], lr, step_no)
                new_oth_m.append(np_)
                if offload_p and comp_res:
                    new_oth_c.append(bf16_of(np_))
                new_oth_o.append(ns)
                chain.append(np_)

            if offload_p:
                out_blk_m = new_m
                out_blk_c = {s: jnp.stack(new_c[s], 1)
                             for s in sfx_list} if comp_res else {}
            else:
                out_blk_m = {s: jnp.stack(new_m[s], 1) for s in sfx_list}
                out_blk_c = {}
            if offload_o:
                out_blk_o = new_o
            else:
                out_blk_o = {s: {k: jnp.stack(
                    [new_o[s][i][k] for i in range(lps)], 1)
                    for k in blk_o[s]} for s in sfx_list}
            return (loss, out_blk_m, new_oth_m, out_blk_c, new_oth_c,
                    out_blk_o, new_oth_o)

        ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
        pns = self._param_ns
        ons = self._opt_ns
        if offload_p:
            blk_m_sh = {s: [pns(self.block_layer_specs[s])] * lps
                        for s in sfx_list}
            if comp_res:
                blk_c_sh = {s: ns(self.block_specs[s])
                            for s in sfx_list}
                oth_c_sh = [ns(sp) for sp in self.other_specs]
            else:
                blk_c_sh, oth_c_sh = {}, []
        else:
            blk_m_sh = {s: pns(self.block_specs[s]) for s in sfx_list}
            blk_c_sh, oth_c_sh = {}, []
        oth_m_sh = [pns(sp) for sp in self.other_specs]
        if offload_o:
            blk_o_sh = {s: [{k: ons(v) for k, v in
                             self.block_opt_specs[s].items()}] * lps
                        for s in sfx_list}
        else:
            blk_o_sh = {s: {k: ons(v) for k, v in
                            self.block_opt_specs[s].items()}
                        for s in sfx_list}
        oth_o_sh = [{k: ons(v) for k, v in d.items()}
                    for d in self.other_opt_specs]
        self._batch_spec = self._make_batch_spec()
        self._step_fn = jax.jit(
            step_fn,
            in_shardings=(blk_m_sh, oth_m_sh, blk_c_sh, oth_c_sh,
                          blk_o_sh, oth_o_sh, None, None, None, None),
            out_shardings=(ns(P()), blk_m_sh, oth_m_sh, blk_c_sh,
                           oth_c_sh, blk_o_sh, oth_o_sh),
            donate_argnums=(0, 1, 2, 3, 4, 5))
        self._n_batch_args = n_batch_args

    def _state_args(self):
        if self.stream_layers:
            return (self.block_vals, self.other_vals, self.block_comp,
                    self.other_comp, self.block_opt, self.other_opt)
        return (self.block_vals, self.other_vals, self.block_opt,
                self.other_opt)

    def step(self, *batch) -> jax.Array:
        from ..core import rng as rng_mod

        if self.abstract:
            raise RuntimeError(
                "This trainer was built from a LazyGuard (abstract) model "
                "— it can plan (memory_analysis / aot_lower) but not "
                "execute. Materialize the model (framework.lazy."
                "materialize) and rebuild the trainer to train.")
        if self._step_fn is None or self._n_batch_args != len(batch):
            self._build(len(batch))
        self._step += 1
        # zero-overhead-when-disabled guard: one bool read per step; the
        # instrumented branch additionally SYNCS on the loss (a host value
        # fetch — the only truthful step boundary, bench.py NOTE), so the
        # enabled step_ms histogram measures execution, not dispatch.
        prof = _ptrace.is_enabled()
        t0 = time.perf_counter_ns() if prof else 0
        h2d = _ptrace.scope("hybrid/h2d") if prof else contextlib.nullcontext()
        with h2d:
            vs = self._stage_batch(batch)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        args = (*self._state_args(), vs, lr,
                jnp.asarray(self._step, jnp.int32), rng_mod.next_key())
        if self.guard_bad_steps:
            # fault defaults to the exact-noop 1.0; a pending injection
            # (inject_fault_scale) poisons exactly this one step
            args = args + (jnp.asarray(
                1.0 if self._fault_scale is None else self._fault_scale,
                jnp.float32),)
            self._fault_scale = None
        if prof:
            # profiled_step_sync (default True): sync on the loss so the
            # step_ms histogram measures execution, not dispatch. An
            # async-dispatch loop (elastic.py) sets it False — forcing a
            # per-step sync here would serialize the very overlap being
            # measured — and the deferred materialization records the
            # honest hybrid/sync_wait span instead; the histogram is
            # then named hybrid/dispatch_ms, because that is what it is.
            sync = getattr(self, "profiled_step_sync", True)
            with _ptrace.scope("hybrid/step"):
                out = self._step_fn(*args)
                if sync:
                    # truthful sync; the inner span isolates how much of
                    # the step was execution the host WAITED on vs
                    # dispatch (the gap the async pipeline hides)
                    with _ptrace.scope("sync_wait"):
                        float(np.asarray(out[0]))
            dt_ms = (time.perf_counter_ns() - t0) / 1e6
            reg = _preg()
            reg.counter("train/steps").add(1)
            reg.counter("train/tokens").add(_pinstr.tokens_in_batch(vs))
            reg.histogram("hybrid/step_ms" if sync
                          else "hybrid/dispatch_ms").observe(dt_ms)
            _pinstr.record_memory_high_water()
        else:
            out = self._step_fn(*args)
        if self.guard_bad_steps:
            self._last_ok_dev = out[1]
            out = (out[0],) + out[2:]
        if self.stream_layers:
            (loss, self.block_vals, self.other_vals, self.block_comp,
             self.other_comp, self.block_opt, self.other_opt) = out
        else:
            (loss, self.block_vals, self.other_vals, self.block_opt,
             self.other_opt) = out
        self.optimizer._global_step = self._step
        return loss

    __call__ = step

    # -- bad-step guard surface (paddle_tpu.resilience) --------------------
    @property
    def last_step_ok(self) -> bool:
        """Verdict of the most recent guarded step (True before any step
        or when the guard is off). Reading it syncs on the tiny verdict
        scalar — the resilient runner already syncs on the loss, so this
        costs nothing extra there."""
        if self._last_ok_dev is None:
            return True
        return bool(np.asarray(self._last_ok_dev))

    def last_step_ok_device(self):
        """The guarded verdict of the most recent step as the DEVICE
        scalar (None before any guarded step) — the async step
        pipeline's deferred-sync handle: the resilient runner captures
        it per dispatched step and materializes a whole window at its
        sync points instead of paying a host round-trip every step."""
        return self._last_ok_dev

    def inject_fault_scale(self, value: float) -> None:
        """Chaos hook: multiply the NEXT step's loss by ``value`` (NaN
        poisons loss and every gradient). One-shot; requires
        guard_bad_steps so the poison cannot reach the weights."""
        if not self.guard_bad_steps:
            raise RuntimeError(
                "inject_fault_scale requires guard_bad_steps=True — "
                "injecting a NaN without the guard would poison the "
                "weights permanently")
        self._fault_scale = float(value)

    def _stage_arg(self, b):
        v = b._value if isinstance(b, Tensor) else jnp.asarray(b)
        return jax.device_put(v, NamedSharding(
            self.mesh, self._batch_spec(v.ndim)))

    def _stage_batch(self, batch) -> tuple:
        """Device-put each batch element with the trainer's batch
        sharding — the ONE staging definition; step(),
        profile_step_phases() and aot_lower() must place batches
        identically or their programs would not cache-share."""
        return tuple(self._stage_arg(b) for b in batch)

    def profile_step_phases(self, *batch, iters: int = 2,
                            trace_window: int = 0):
        """Per-phase (fwd/bwd/optim/comm) decomposition of the train
        step, recorded as ``phase/*_ms`` gauges — what
        ``profiler.summary()["phases_ms"]`` reports.

        The step is ONE fused pjit program, so phases cannot be
        host-timed inside it; nested prefixes are compiled and timed
        instead — fwd (loss only), fwd+bwd (value_and_grad), full step —
        and bwd = fwdbwd − fwd, optim = step − fwdbwd. ``comm`` is a
        two-number split (profiler.instrument.record_phases): the
        nominal-bandwidth model (``phase/comm_ms`` — collective bytes
        over link rate) AND measured step wall time apportioned by
        XLA's cost-analysis byte accounting
        (``phase/comm_measured_ms`` — real clock, modeled
        attribution); both 0 on one chip. Also folds the step program's
        compile wall-time + cost-analysis FLOPs/bytes into the
        profiler's program inventory (xla_stats, keyed by the
        ``hybrid.step#N`` site). Costs three
        extra diagnostic compiles (fwd, fwd+bwd, and the timed
        inventory compile) and runs ``iters`` REAL optimizer steps
        (training state advances). Offload/stream configs skip the fwd/bwd split
        (their step streams host-resident state the sub-programs would
        misattribute) and report step + comm only.

        ``trace_window=k`` (ISSUE 11) additionally wraps ``k`` MORE
        real steps in a parsed device-trace capture
        (profiler.device_trace): measured per-op-category timings,
        per-collective durations by kind, the compute∩comm overlap
        fraction (``phase/comm_traced_ms`` / ``phase/comm_overlap_frac``
        — MEASURED, next to the apportioned ``phase/comm_measured_ms``)
        and the goodput/MFU ledger, returned under the ``"trace"`` key.
        On CPU the trace measures XLA:CPU thunks (host-scheduled —
        overlap ~0 by construction; stated in device_trace docs).
        """
        from ..core import rng as rng_mod

        if self._step_fn is None or self._n_batch_args != len(batch):
            self._build(len(batch))
        vs = self._stage_batch(batch)
        key = rng_mod.next_key()

        t_fwd = t_fb = None
        if not (self.stream_layers or self.offload_params):
            fwd = jax.jit(lambda bp, op: self._forward_loss(
                bp, op, vs, key))
            t_fwd = _pinstr.time_compiled(
                lambda: fwd(self.block_vals, self.other_vals), iters)
            fb = jax.jit(lambda bp, op: jax.value_and_grad(
                lambda b_, o_: self._forward_loss(b_, o_, vs, key),
                argnums=(0, 1))(bp, op))
            t_fb = _pinstr.time_compiled(
                lambda: fb(self.block_vals, self.other_vals), iters)
        t_step = _pinstr.time_compiled(lambda: self.step(*batch), iters)

        lowered = self.aot_lower(*batch)
        st = _pinstr.record_collectives_from(lowered, self.mesh)
        # compiled-program accounting: compile wall-time + XLA's own
        # cost analysis into the program inventory, keyed by the same
        # site name the retrace telemetry uses — and the cost-analysis
        # byte total turns the comm phase into a measured/estimated
        # split (phase/comm_measured_ms: measured step time apportioned
        # by collective-byte share) next to the nominal-bandwidth model
        from ..profiler import xla_stats as _xstats

        ps = _xstats.record_lowered(self._prof_site, lowered)
        out = _pinstr.record_phases(
            fwd_s=t_fwd, fwdbwd_s=t_fb, step_s=t_step,
            comm_bytes=st["total_bytes"], platform=_target_platform(),
            cost_bytes_accessed=ps.bytes_accessed)
        if trace_window:
            # record_lowered above registered the step program's HLO
            # module name, so the parsed slices attribute to
            # hybrid.step#N; each step syncs (time_compiled idiom) so
            # no device work is cut off when the trace stops
            from ..profiler import device_trace as _dtrace

            with _dtrace.capture(steps=int(trace_window),
                                 label=self._prof_site) as cap:
                for _ in range(int(trace_window)):
                    _pinstr._first_leaf(self.step(*batch))
            out["trace"] = cap.summary
        return out

    def memory_analysis(self, *batch):
        """Compiled-memory report of the train step (bytes), from XLA's
        buffer assignment — the only truthful HBM accounting under a
        remote-device tunnel where ``Device.memory_stats()`` is None.
        ``peak ≈ arguments − aliased + temps`` (donated state re-uses its
        argument buffers; offloaded state is host-resident and excluded
        from the HBM argument total by XLA's per-space accounting)."""
        ma = self.aot_compile(*batch).memory_analysis()
        if ma is None:
            return None
        out = {k: int(getattr(ma, k)) for k in
               ("argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes") if hasattr(ma, k)}
        if {"argument_size_in_bytes", "temp_size_in_bytes",
                "alias_size_in_bytes"} <= out.keys():
            out["peak_bytes_est"] = (out["argument_size_in_bytes"]
                                     - out["alias_size_in_bytes"]
                                     + out["temp_size_in_bytes"])
        if self.offload_params or self.offload_optimizer:
            # split HBM vs host arguments (r3 "cannot split" note closed):
            # XLA's argument total folds pinned_host args in, but WE know
            # exactly which state the trainer placed host-side — subtract
            # its bytes to get the HBM-resident argument set.
            host = 0

            def nbytes(v):
                return int(np.prod(v.shape)) * jnp.dtype(v.dtype).itemsize

            leaves = jax.tree_util.tree_leaves
            if self.offload_params:
                host += sum(nbytes(v) for v in leaves(self.block_vals))
                host += sum(nbytes(v) for v in leaves(self.other_vals))
            if self.offload_optimizer:
                host += sum(nbytes(v) for v in leaves(self.block_opt))
                host += sum(nbytes(v) for v in leaves(self.other_opt))
            out["host_resident_argument_bytes"] = host
            args = out.get("argument_size_in_bytes", 0)
            if args >= host:
                out["hbm_argument_bytes"] = args - host
                if "peak_bytes_est" in out:
                    out["hbm_peak_bytes_est"] = max(
                        out["peak_bytes_est"] - host, 0)
            else:
                # this toolchain build already excluded host-space args
                # from its per-space totals — subtracting again would
                # double-count (seen at 1.9B: args < host bytes)
                out["hbm_argument_bytes"] = args
                if "peak_bytes_est" in out:
                    out["hbm_peak_bytes_est"] = out["peak_bytes_est"]
        return out

    def aot_lower(self, *batch):
        """AOT-lower the train step without executing anything. ``batch``
        entries may be Tensors, arrays, or ``jax.ShapeDtypeStruct``s
        (required in abstract/LazyGuard mode — nothing is materialized
        anywhere in that path)."""
        if self._step_fn is None or self._n_batch_args != len(batch):
            self._build(len(batch))
        vs = []
        for b in batch:
            if isinstance(b, jax.ShapeDtypeStruct):
                vs.append(jax.ShapeDtypeStruct(
                    tuple(b.shape), b.dtype, sharding=NamedSharding(
                        self.mesh, self._batch_spec(len(b.shape)))))
            else:
                vs.append(self._stage_arg(b))
        # constant key: only avals matter for lowering, and a diagnostic
        # must not advance the training RNG stream. suppressed(): this
        # re-trace is by design, not a silent recompile — keep it out of
        # the profiler's retrace counter/log.
        tail = ((jax.ShapeDtypeStruct((), jnp.float32),)
                if self.guard_bad_steps else ())
        with _precomp.suppressed():
            return self._step_fn.lower(
                *self._state_args(), tuple(vs),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((2,), jnp.uint32), *tail)

    def aot_compile(self, *batch):
        return self.aot_lower(*batch).compile()

    # -- sharded checkpoint integration (distributed/checkpoint.py) -------
    def device_state(self):
        """The trainer's on-device state as one pytree of sharded arrays
        (params + optimizer state), for distributed.checkpoint.save."""
        return {"block": dict(self.block_vals),
                "other": list(self.other_vals),
                "block_opt": {k: list(v) if isinstance(v, list)
                              else dict(v)
                              for k, v in self.block_opt.items()},
                "other_opt": [dict(d) for d in self.other_opt]}

    def load_device_state(self, st, step: Optional[int] = None):
        """Inverse of device_state (resume-exact: same values, shardings)."""
        self.block_vals = dict(st["block"])
        self.other_vals = list(st["other"])
        self.block_opt = {k: list(v) if isinstance(v, list) else dict(v)
                          for k, v in st["block_opt"].items()}
        self.other_opt = [dict(d) for d in st["other_opt"]]
        if self.stream_layers and self.offload_params \
                and self.comp_resident:
            # the bf16 compute copies are derived state (comp ≡
            # bf16(master) after every update) — rebuild, don't persist
            def dev_bf16(p, spec):
                d = jax.device_put(p, NamedSharding(self.mesh, spec))
                return d.astype(jnp.bfloat16) \
                    if jnp.issubdtype(d.dtype, jnp.floating) else d

            self.block_comp = {
                sfx: jax.device_put(
                    jnp.stack([dev_bf16(p, self.block_layer_specs[sfx])
                               for p in pieces], 1),
                    NamedSharding(self.mesh, self.block_specs[sfx]))
                for sfx, pieces in self.block_vals.items()}
            self.other_comp = [
                jax.device_put(dev_bf16(v, spec),
                               NamedSharding(self.mesh, spec))
                for v, spec in zip(self.other_vals, self.other_specs)]
        if step is not None:
            self._step = int(step)
            self.optimizer._global_step = int(step)

    def memory_ledger(self) -> dict:
        """Per-rank resident bytes by state category, from ACTUAL array
        shardings (profiler.record_memory_ledger — gauges
        ``mem/{param,grad,opt_state,master}_bytes``). On the manual
        ZeRO path opt state (and master) are [dp*chunk] slabs sharded
        P('dp'), so their per-rank count is 1/dp of the replicated
        baseline; ``grad`` is the transient fused gradient buffer,
        counted at its full-size per-rank peak (pre-reduce-scatter)."""
        params = (self.block_vals, self.other_vals)
        cats = {"param": params,
                "grad": 4 * sum(int(np.prod(v.shape))
                                for v in jax.tree_util.tree_leaves(
                                    params))}
        if self.zero_manual:
            slab = self.block_opt[_ZERO_SLAB]
            cats["opt_state"] = {k: v for k, v in slab.items()
                                 if k != "master"}
            if "master" in slab:
                cats["master"] = slab["master"]
        else:
            cats["opt_state"] = (self.block_opt, self.other_opt)
        return _pinstr.record_memory_ledger(cats)

    def _unflatten_zero_opt(self):
        """Regather the fused dp-sharded ZeRO slabs and slice them back
        into the per-suffix / per-other optimizer-state layout
        (host-side; sync_to_layer path only). Slice order is the
        tree_flatten order the slabs were built in: sorted block
        suffixes, then the other-param list."""
        flat = {k: np.asarray(v)
                for k, v in self.block_opt[_ZERO_SLAB].items()
                if k != "master"}
        blk, oth, off = {}, [], 0
        for sfx in sorted(self.block_vals.keys()):
            shape = tuple(self.block_vals[sfx].shape)
            sz = int(np.prod(shape))
            blk[sfx] = {k: jnp.asarray(v[off:off + sz].reshape(shape))
                        for k, v in flat.items()}
            off += sz
        for v in self.other_vals:
            shape = tuple(v.shape)
            sz = int(np.prod(shape))
            oth.append({k: jnp.asarray(v2[off:off + sz].reshape(shape))
                        for k, v2 in flat.items()})
            off += sz
        return blk, oth

    def sync_to_layer(self):
        """Unstack device state (params AND optimizer accumulators) back
        into the eager model/optimizer, so state_dict/checkpoints see the
        trained values."""
        L = self.n_layers
        blk_opt_src, oth_opt_src = (self._unflatten_zero_opt()
                                    if self.zero_manual
                                    else (self.block_opt,
                                          self.other_opt))

        def unstack(a):
            if isinstance(a, list):
                # stream_layers per-layer pieces [pp, ...] → [pp, lps, ..]
                a = jnp.stack(
                    [jax.device_put(
                        p, NamedSharding(self.mesh, p.sharding.spec))
                     if getattr(p.sharding, "memory_kind", None)
                     == "pinned_host" else p for p in a], 1)
            if getattr(a.sharding, "memory_kind", None) == "pinned_host":
                a = jax.device_put(
                    a, NamedSharding(self.mesh, a.sharding.spec))
            if self.v == 1:
                return a.reshape((L,) + tuple(a.shape[2:]))
            # invert the circular assignment: [pp, v, lps_v, ...] -> [L,...]
            return jnp.swapaxes(a, 0, 1).reshape((L,) + tuple(a.shape[3:]))

        for sfx_i, sfx in enumerate(self.block_suffixes):
            stacked = self.block_vals[sfx]
            flat = unstack(stacked)
            opt_src = blk_opt_src[sfx]
            if isinstance(opt_src, list):   # stream per-layer dicts
                opt_src = {k: [d[k] for d in opt_src]
                           for k in opt_src[0]}
            opt_flat = {k: unstack(v) for k, v in opt_src.items()}
            for i in range(L):
                t = self._per_block_tensors[i][sfx_i]
                t._value = flat[i]
                self.optimizer._accumulators[id(t)] = {
                    k: v[i] for k, v in opt_flat.items()}
        for n, v, s in zip(self.other_names, self.other_vals,
                           oth_opt_src):
            t = self._name2tensor[n]
            if getattr(v.sharding, "memory_kind", None) == "pinned_host":
                v = jax.device_put(
                    v, NamedSharding(self.mesh, v.sharding.spec))
            t._value = v
            self.optimizer._accumulators[id(t)] = s
        return self.model
