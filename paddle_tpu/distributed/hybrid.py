"""Model-agnostic hybrid-parallel trainer: dp × tp × pp × sp × ZeRO in ONE
pjit program.

The reference composes parallelism with a chain of meta-optimizers that
rewrite per-rank programs around USER model code (reference:
fleet/meta_optimizers/{sharding,pipeline,amp,recompute}_optimizer.py chained
by fleet/base/strategy_compiler.py; the pipeline splitter keys on per-op
device attributes, pipeline_optimizer.py:136) — model-agnostic by operating
on the program graph. Here the trainer is model-agnostic by a three-method
protocol any stacked-block model declares (models/gpt.py, models/bert.py):

  pipeline_stem(*batch)  -> activations       (embeddings)
  pipeline_blocks()      -> list of identical blocks (stackable params)
  pipeline_head(x, *batch) -> scalar loss     (norm + head + loss)

The trainer stacks block params to [pp, layers_per_stage, ...], shards the
stage axis over 'pp' (pipeline.py shard_map), scans/unrolls layers within a
stage, shards batch dim 0 over 'dp' (+ seq dim 1 over 'sp'), applies ZeRO
1/2/3 by adding a 'dp' axis to opt-state/param shardings, bf16-casts under
amp, and wraps blocks in jax.checkpoint under recompute — all in one jitted
step XLA can schedule globally.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.place import target_platform as _target_platform
from ..framework.tensor import Tensor
from ..static.functional import _swapped_state, state_tensors
from .fleet.distributed_strategy import DistributedStrategy
from .pipeline import pipeline_apply
from .strategy_compiler import (_add_axis, _local_check_shape,
                                build_mesh_from_strategy,
                                resolve_param_specs)


def _check_protocol(model):
    for m in ("pipeline_stem", "pipeline_blocks", "pipeline_head"):
        if not hasattr(model, m):
            raise TypeError(
                f"{type(model).__name__} does not implement the pipeline "
                f"protocol ({m}); see distributed/hybrid.py docstring")


class HybridPipelineTrainer:
    """Compiled hybrid-parallel trainer for any pipeline-protocol model."""

    def __init__(self, model, optimizer,
                 strategy: Optional[DistributedStrategy] = None,
                 mesh: Optional[Mesh] = None, n_micro: Optional[int] = None,
                 v_virtual: Optional[int] = None,
                 remat_policy: Optional[str] = None,
                 param_dtype=None, moment_dtype=None,
                 offload_optimizer: bool = False,
                 offload_params: bool = False,
                 offload_depth: int = 2,
                 update_scan: bool = False,
                 unroll_layers: Optional[bool] = None,
                 free_eager: bool = False):
        """Memory knobs for billion-param single/few-chip configs
        (reference analogue: RecomputeConfig offload + ShardingConfig,
        distributed_strategy.proto:25-35):

        param_dtype:  storage dtype of the master params (default f32;
            'bfloat16' halves param memory — the update still computes
            in f32 and casts back).
        moment_dtype: storage dtype of optimizer moments (e.g.
            'bfloat16' halves AdamW state; update math stays f32).
        offload_optimizer: place optimizer state in pinned_host memory
            (the ZeRO-offload idea via XLA memory kinds). State streams
            host→HBM around the update each step — measured ~12 GB/s
            effective on a v5e host link, so this trades step time for
            HBM; use for models whose state cannot fit at any dtype.
        offload_params: ZeRO-Offload layout — the f32 master params live
            in pinned_host memory; each step streams them to HBM, casts
            to bf16 compute copies (grads are then bf16, halving grad
            HBM), and the f32 update streams master+moments through HBM
            per parameter group before writing back to host. Requires
            amp. This is the full-fidelity path for models whose f32
            master + f32 grads cannot fit HBM (1.3B+ on one 16 GB v5e).
        unroll_layers: unroll the per-stage layer loop. Default: unroll
            on TPU without remat (removes the scan's dynamic-slice
            bookkeeping), scan under remat — unrolling a rematerialized
            backward lets the latency-hiding scheduler hoist every
            layer's recomputation early, holding dozens of ffn
            intermediates live at once (measured 31% HBM fragmentation
            at 1.3B); the scan keeps layer backward strictly
            sequential so one layer's working set bounds live memory.
        free_eager: delete the eager model's device buffers after the
            trainer stacks/casts its own copies — at 1.3B the eager f32
            params are 5.3 GB of HBM that would sit dead next to the
            trainer's bf16 state. ``sync_to_layer`` restores them."""
        _check_protocol(model)
        # MoE composes with pp: blocks return (h, aux) and pipeline_apply
        # carries the load-balance scalar across the schedule (stage_aux)
        cfg = getattr(model, "config", None)
        self.moe = bool(getattr(cfg, "moe_num_experts", 0))
        self.moe_aux_weight = float(getattr(cfg, "moe_aux_weight", 0.0))
        self.model = model
        self.optimizer = optimizer
        self.strategy = strategy or DistributedStrategy()
        self.mesh = mesh if mesh is not None else \
            build_mesh_from_strategy(self.strategy)
        self.pp = self.mesh.shape.get("pp", 1)
        self.n_micro = n_micro or max(
            self.strategy.pipeline_configs.accumulate_steps,
            self.strategy.pipeline_configs.micro_batch, self.pp)
        # interleaved/circular schedule degree (pipeline.py): v virtual
        # stages per device shrink the bubble v×
        self.v = v_virtual or getattr(self.strategy.pipeline_configs,
                                      "virtual_pipeline_degree", 1) or 1
        self.amp = self.strategy.amp
        self.remat = self.strategy.recompute
        # remat_policy "dots": selective remat — matmul outputs are saved,
        # elementwise/softmax recomputed. Most of full remat's memory win
        # at a fraction of its FLOP cost (full remat re-runs the matmuls
        # too, reference RecomputeOptimizer semantics).
        self.remat_policy = remat_policy
        self.zero = self.strategy.sharding_configs.sharding_stage \
            if self.strategy.sharding else 0
        self.param_dtype = jnp.dtype(param_dtype) if param_dtype else None
        self.moment_dtype = jnp.dtype(moment_dtype) if moment_dtype \
            else None
        self.offload_optimizer = offload_optimizer
        self.offload_params = offload_params
        # host↔HBM streaming pipeline depth: how many per-group f32
        # (p, m, v) working sets may be in flight at once. Deeper = more
        # copy/compute overlap, +1 group of transient HBM per step
        self.offload_depth = max(1, int(offload_depth))
        # update_scan: run the stacked-group optimizer update as a
        # lax.scan over layers — bounds f32 update transients to one
        # layer instead of a whole group. Opt-in: this environment's
        # remote compile helper SIGABRTs on the scan+offload composition
        # for some configs, so the default keeps the validated whole-
        # group update.
        self.update_scan = bool(update_scan)
        if offload_params and not self.amp:
            raise ValueError("offload_params requires strategy.amp (the "
                             "compute copies are bf16)")
        self.unroll_layers = unroll_layers

        self._param_ns = lambda sp: NamedSharding(
            self.mesh, sp, memory_kind="pinned_host") \
            if self.offload_params else NamedSharding(self.mesh, sp)

        blocks = list(model.pipeline_blocks())
        L = len(blocks)
        if L % (self.pp * self.v) != 0:
            raise ValueError(
                f"{L} blocks must be divisible by pp_degree×v_virtual="
                f"{self.pp}×{self.v}")
        self.lps = L // self.pp
        self.n_layers = L

        # --- split state: block params (stacked) vs the rest --------------
        pn, pt, bn, bt = state_tensors(model)
        name_by_id = {id(t): n for n, t in zip(pn, pt)}
        base_specs = resolve_param_specs(model, self.mesh, zero_stage=0)

        sfx0, t0 = state_tensors(blocks[0])[:2]
        self.block_suffixes = list(sfx0)
        self._blk0_tensors = list(t0)
        self._blk0_fullnames = [name_by_id[id(t)] for t in t0]
        per_block_tensors: List[List[Tensor]] = [t0]
        block_ids = set(id(t) for t in t0)
        for b in blocks[1:]:
            sfx_i, t_i = state_tensors(b)[:2]
            if list(sfx_i) != self.block_suffixes:
                raise ValueError(
                    "pipeline blocks must have identical structure; "
                    f"{sfx_i} != {self.block_suffixes}")
            per_block_tensors.append(list(t_i))
            block_ids.update(id(t) for t in t_i)

        self.other_names = [n for n, t in zip(pn, pt)
                            if id(t) not in block_ids]
        name2t = dict(zip(pn, pt))
        self._name2tensor = name2t
        self._per_block_tensors = per_block_tensors

        # LazyGuard (framework/lazy.py) models: every param is a
        # ShapeDtypeStruct. The trainer then *plans* instead of allocating
        # — stack/cast/shard through jax.eval_shape, optimizer state via
        # eval_shape of _init_state, and step() is AOT-only
        # (lower/compile/memory_analysis). This is the 13B path: planning
        # a 156 GB-state model allocates nothing anywhere.
        from ..framework.lazy import is_abstract
        self.abstract = any(is_abstract(t) for t in pt)

        dp = self.mesh.shape.get("dp", 1)

        # stacked block params: [pp, lps, ...] (GPipe) or
        # [pp, v, lps/v, ...] (interleaved: stage s circuit c owns layers
        # (c·pp + s)·lps_v .. +lps_v — the circular assignment)
        self.block_vals: Dict[str, jax.Array] = {}
        self.block_specs: Dict[str, P] = {}
        for j, sfx in enumerate(self.block_suffixes):
            base = per_block_tensors[0][j]._value
            if self.v == 1:
                full_shape = (self.pp, self.lps) + tuple(base.shape)
                extra = (None,)
            else:
                lps_v = self.lps // self.v
                full_shape = (self.pp, self.v, lps_v) + tuple(base.shape)
                extra = (None, None)
            if self.abstract:
                stacked = jax.ShapeDtypeStruct(full_shape, base.dtype)
            else:
                per_layer = [per_block_tensors[i][j]._value
                             for i in range(L)]
                stacked = jnp.stack(per_layer, 0)
                if self.v == 1:
                    stacked = stacked.reshape(full_shape)
                else:
                    stacked = stacked.reshape(
                        (self.v, self.pp, lps_v) + per_layer[0].shape)
                    stacked = jnp.swapaxes(stacked, 0, 1)  # [pp,v,lps_v,...]
            spec0 = base_specs[self._blk0_fullnames[j]]
            pp_ax = "pp" if "pp" in self.mesh.axis_names else None
            spec = P(pp_ax, *extra, *spec0)
            if self.zero >= 3:
                shape = _local_check_shape(stacked.shape, spec, self.mesh)
                spec = _add_axis(spec, stacked.ndim, shape, "dp", dp)
            self.block_specs[sfx] = spec
            dt = stacked.dtype
            if self.param_dtype is not None and \
                    jnp.issubdtype(dt, jnp.floating):
                dt = self.param_dtype
            if self.abstract:
                self.block_vals[sfx] = jax.ShapeDtypeStruct(
                    full_shape, dt, sharding=self._param_ns(spec))
            else:
                if dt != stacked.dtype:
                    stacked = stacked.astype(dt)
                self.block_vals[sfx] = jax.device_put(
                    stacked, self._param_ns(spec))

        self.other_vals: List[jax.Array] = []
        self.other_specs: List[P] = []
        for n in self.other_names:
            spec = base_specs[n]
            t = name2t[n]
            if self.zero >= 3:
                shape = _local_check_shape(t._value.shape, spec, self.mesh)
                spec = _add_axis(spec, t._value.ndim, shape, "dp", dp)
            self.other_specs.append(spec)
            v = t._value
            dt = v.dtype
            if self.param_dtype is not None and \
                    jnp.issubdtype(dt, jnp.floating):
                dt = self.param_dtype
            if self.abstract:
                self.other_vals.append(jax.ShapeDtypeStruct(
                    tuple(v.shape), dt, sharding=self._param_ns(spec)))
            else:
                if dt != v.dtype:
                    v = v.astype(dt)
                self.other_vals.append(jax.device_put(
                    v, self._param_ns(spec)))

        # --- optimizer state ----------------------------------------------
        def opt_state_spec(spec, shape, ndim):
            if self.zero >= 1:
                local = _local_check_shape(shape, spec, self.mesh)
                return _add_axis(spec, ndim, local, "dp", dp)
            return spec

        class _FakeParam:
            def __init__(self, v):
                self._value = v

        def cast_state(s):
            if self.moment_dtype is None:
                return s
            return {k: v.astype(self.moment_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in s.items()}

        self._opt_ns = lambda sp: NamedSharding(
            self.mesh, sp, memory_kind="pinned_host") \
            if self.offload_optimizer else NamedSharding(self.mesh, sp)

        def init_opt_state(v, sp):
            """Optimizer state for one (stacked) param: real arrays
            normally; shape-only (eval_shape of _init_state) in abstract
            mode, with the moment-dtype cast applied to the metadata."""
            if not self.abstract:
                s = cast_state(optimizer._init_state(_FakeParam(v)))
                return jax.device_put(s, {k: self._opt_ns(sp) for k in s})
            s = jax.eval_shape(
                lambda vv: optimizer._init_state(_FakeParam(vv)),
                jax.ShapeDtypeStruct(v.shape, v.dtype))
            out = {}
            for k, sd in s.items():
                dt = sd.dtype
                if self.moment_dtype is not None and \
                        jnp.issubdtype(dt, jnp.floating):
                    dt = self.moment_dtype
                out[k] = jax.ShapeDtypeStruct(
                    tuple(sd.shape), dt, sharding=self._opt_ns(sp))
            return out

        self.block_opt: Dict[str, dict] = {}
        self.block_opt_specs: Dict[str, dict] = {}
        for sfx, v in self.block_vals.items():
            sp = opt_state_spec(self.block_specs[sfx], v.shape, v.ndim)
            s = init_opt_state(v, sp)
            self.block_opt[sfx] = s
            self.block_opt_specs[sfx] = {k: sp for k in s}
        self.other_opt: List[dict] = []
        self.other_opt_specs: List[dict] = []
        for n, v, spec in zip(self.other_names, self.other_vals,
                              self.other_specs):
            sp = opt_state_spec(spec, v.shape, v.ndim)
            s = init_opt_state(v, sp)
            self.other_opt.append(s)
            self.other_opt_specs.append({k: sp for k in s})

        if free_eager and not self.abstract:
            # device_put may return a NEW Array sharing the SAME buffer
            # when dtype+sharding are unchanged, so aliasing cannot be
            # detected by identity. Delete only buffers that are
            # provably fresh copies: per-layer block params (jnp.stack
            # always materializes a new stacked buffer) and other params
            # whose dtype cast forced a copy. An uncast "other" param
            # keeps sharing its buffer with the trainer — dropping the
            # eager reference alone still releases nothing extra, and
            # deleting would kill the trainer's own state.
            for ts in per_block_tensors:
                for t in ts:
                    t._value.delete()
                    t._value = None
            for n, v in zip(self.other_names, self.other_vals):
                t = name2t[n]
                if t._value.dtype != v.dtype:
                    t._value.delete()
                t._value = None

        self._step = 0
        self._n_batch_args: Optional[int] = None
        self._step_fn = None

    # ---------------------------------------------------------------------
    def _forward_loss(self, block_params, other_params, batch, key):
        model = self.model
        from ..core import rng as rng_mod

        if self.amp:
            castf = lambda v: v.astype(jnp.bfloat16) if \
                jnp.issubdtype(v.dtype, jnp.floating) else v
        else:
            castf = lambda v: v
        other_cast = [castf(v) for v in other_params]
        block_cast = {k: castf(v) for k, v in block_params.items()}

        other_tensors = [self._name2tensor[n] for n in self.other_names]
        blk0_tensors = self._blk0_tensors
        sp = self.mesh.shape.get("sp", 1)

        def seq_constraint(h):
            """Keep activations sequence-sharded between ring attentions.
            Skipped for bf16 on XLA:CPU (tests): resharding constraints on
            bf16 trip a CPU-backend crash; TPU is unaffected."""
            if sp > 1 and not (_target_platform() == "cpu"
                               and h.dtype == jnp.bfloat16):
                return jax.lax.with_sharding_constraint(
                    h, NamedSharding(self.mesh, P("dp", "sp", None)))
            return h

        from . import context as dctx
        manual_sp = sp > 1 and self.pp > 1
        block0 = model.pipeline_blocks()[0]

        moe = self.moe
        aux_w = self.moe_aux_weight

        def block_apply(stage_local, x):
            """Apply one stage's lps blocks (lax.scan over layers).
            MoE models: returns (out, weighted aux-loss sum of the
            stage's blocks) — the pipeline's stage_aux contract."""
            # axes that stay GSPMD-auto inside the manual-pp region:
            # pallas kernels must nest a shard_map over them (Mosaic
            # cannot be auto-partitioned in a partially-manual region).
            # pp == 1 runs fully auto — no scope needed.
            auto_axes = tuple(a for a in self.mesh.axis_names
                              if a != "pp" and not (manual_sp and a == "sp"))
            auto_scope = (
                (lambda: dctx.pipeline_auto_axes_scope(self.mesh,
                                                       auto_axes))
                if self.pp > 1 else contextlib.nullcontext)

            def one_block(h, layer_params):
                vals = [layer_params[s] for s in self.block_suffixes]
                with _swapped_state(blk0_tensors, vals), auto_scope():
                    if manual_sp:
                        with dctx.manual_sequence_parallel_scope():
                            out = block0(Tensor(h))._value
                    else:
                        out = block0(Tensor(h))._value
                    aux = block0.mlp._aux._value if moe else None
                return (out, aux) if moe else out

            if self.remat:
                if self.remat_policy == "dots":
                    one_block = jax.checkpoint(
                        one_block,
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable)
                else:
                    one_block = jax.checkpoint(one_block)

            def body(carry, layer_params):
                if moe:
                    h, a = carry
                    out, aux = one_block(h, layer_params)
                    return (out, a + aux.astype(jnp.float32)), None
                return one_block(carry, layer_params), None

            init = (x, jnp.zeros((), jnp.float32)) if moe else x
            unroll = self.unroll_layers if self.unroll_layers is not None \
                else (_target_platform() != "cpu" and not self.remat)
            out, _ = jax.lax.scan(body, init, stage_local, unroll=unroll)
            if moe:
                h, a = out
                return h, a * aux_w
            return out

        batch_tensors = [Tensor(b) for b in batch]
        # loss-inside-pipeline: the head runs in the manual region and only
        # a SCALAR crosses 'pp' (vs the full activation buffer). Disabled
        # under manual sp (head must see the sp-sharded output) and under
        # CPU+amp (bf16 cotangent psum trips XLA:CPU). tp>1 is supported:
        # the vocab-sharded head's tp collectives ride GSPMD-auto inside
        # the manual-pp region like the blocks' do.
        import os
        head_inside = not manual_sp and self.pp > 1 and not (
            _target_platform() == "cpu" and self.amp) and \
            os.environ.get("PADDLE_TPU_HEAD_INSIDE", "1") != "0"
        with _swapped_state(other_tensors, other_cast), \
                dctx.sequence_parallel_scope(self.mesh):
            with rng_mod.key_scope(key):
                x = model.pipeline_stem(*batch_tensors)._value
                x = seq_constraint(x)
                if head_inside:
                    # head params + batch enter the manual region as
                    # explicit inputs; blocks' swapped values are local
                    def head_fn(full, other_vals, batch_vals):
                        with _swapped_state(other_tensors,
                                            list(other_vals)):
                            return model.pipeline_head(
                                Tensor(full),
                                *[Tensor(b) for b in batch_vals])._value
                    loss_v = pipeline_apply(
                        self.mesh, block_apply, block_cast, x,
                        self.n_micro, v_virtual=self.v, head_fn=head_fn,
                        head_args=(tuple(other_cast), tuple(batch)),
                        stage_aux=moe)
                    if moe:
                        loss_v, aux = loss_v
                        return (loss_v + aux).astype(jnp.float32)
                    return loss_v.astype(jnp.float32)
                x = pipeline_apply(self.mesh, block_apply, block_cast, x,
                                   self.n_micro, v_virtual=self.v,
                                   sp_axis="sp" if manual_sp else None,
                                   stage_aux=moe)
                aux = None
                if moe:
                    x, aux = x
                x = Tensor(seq_constraint(x))
                loss = model.pipeline_head(x, *batch_tensors)
                if aux is not None:
                    loss = loss + Tensor(aux)
        return loss._value.astype(jnp.float32)

    def _build(self, n_batch_args: int):
        from .strategy_compiler import functional_clip, make_param_update

        opt = self.optimizer
        clip = opt._grad_clip
        mesh = self.mesh
        wd_other = tuple(opt._decoupled_wd(self._name2tensor[n])
                         for n in self.other_names)
        lr_other = tuple(
            self._name2tensor[n].optimize_attr.get("learning_rate", 1.0)
            for n in self.other_names)
        wd_block = {s: opt._decoupled_wd(t) for s, t in
                    zip(self.block_suffixes, self._blk0_tensors)}
        lr_block = {s: t.optimize_attr.get("learning_rate", 1.0)
                    for s, t in zip(self.block_suffixes,
                                    self._blk0_tensors)}
        upd = make_param_update(opt)

        pdt, mdt = self.param_dtype, self.moment_dtype
        offload = self.offload_optimizer
        mesh_ = self.mesh

        def fetch_state(s, spec):
            """Offload: stream host-resident state into HBM for the
            update (XLA inserts the copies; overlappable by the
            latency-hiding scheduler)."""
            if not offload:
                return s
            return {k: jax.device_put(
                v, NamedSharding(mesh_, spec[k], memory_kind="device"))
                for k, v in s.items()}

        offload_p = self.offload_params

        # update_scan (opt-in): the f32 update math materializes f32
        # copies of a WHOLE stacked group (p, g, m, v — at 2.7B the
        # largest group is 0.84 B params ⇒ ~13 GB of f32 transients,
        # which cannot fit next to the resident bf16 state). Scanning
        # the update over the stacked layer dim bounds the f32 working
        # set to ONE layer; the math is elementwise per parameter so the
        # scan is exact.
        scan_update = self.update_scan

        def core_upd(p, g, s_dev, lr, step_no, plr, wd, store_p_dtype,
                     store_s):
            np_, ns = upd(p, g, s_dev, lr, step_no, plr=plr, wd=wd)
            if pdt is not None and jnp.issubdtype(store_p_dtype,
                                                  jnp.floating):
                np_ = np_.astype(store_p_dtype)
            if mdt is not None:
                ns = {k: v.astype(store_s[k].dtype)
                      if jnp.issubdtype(v.dtype, jnp.floating) else v
                      for k, v in ns.items()}
            return np_, ns

        def upd2(p, g, s, spec, lr, step_no, plr, wd, pspec=None,
                 stacked=False):
            """Update in f32 math, store back at the configured dtypes
            (+ host placement handled by out_shardings when offloading)."""
            if offload_p:
                p = jax.device_put(p, NamedSharding(
                    mesh_, pspec, memory_kind="device"))
            s_dev = fetch_state(s, spec)
            if scan_update and stacked and p.ndim >= 3:
                lead = p.shape[0] * p.shape[1]
                pf = p.reshape((lead,) + p.shape[2:])
                gf = g.reshape((lead,) + g.shape[2:])
                sf = {k: v.reshape((lead,) + v.shape[2:])
                      for k, v in s_dev.items()}

                def body(carry, xs):
                    pi, gi, si = xs
                    npi, nsi = core_upd(pi, gi, si, lr, step_no, plr, wd,
                                        p.dtype, {k: s[k] for k in si})
                    return carry, (npi, nsi)

                _, (npf, nsf) = jax.lax.scan(body, 0, (pf, gf, sf))
                np_ = npf.reshape(p.shape)
                ns = {k: v.reshape(s_dev[k].shape)
                      for k, v in nsf.items()}
                return np_, ns
            return core_upd(p, g, s_dev, lr, step_no, plr, wd, p.dtype, s)

        def step_fn(block_params, other_params, block_opt, other_opt,
                    batch, lr, step_no, key):
            if offload_p:
                # stream masters to HBM and cast; grads flow to the bf16
                # compute copies (half the grad HBM of the f32 path)
                def dev_cast(v, spec):
                    v = jax.device_put(v, NamedSharding(
                        mesh_, spec, memory_kind="device"))
                    return v.astype(jnp.bfloat16) \
                        if jnp.issubdtype(v.dtype, jnp.floating) else v
                bp_c = {k: dev_cast(v, self.block_specs[k])
                        for k, v in block_params.items()}
                op_c = [dev_cast(v, s) for v, s in
                        zip(other_params, self.other_specs)]
            else:
                bp_c, op_c = block_params, other_params

            def loss_of(bp, op):
                return self._forward_loss(bp, op, batch, key)

            loss, (g_blk, g_oth) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(bp_c, op_c)
            g_blk, g_oth = functional_clip(clip, (g_blk, g_oth))

            # offload_params: serialize the per-group host↔HBM update
            # streams (fetch k waits on update k-depth) — unconstrained,
            # the scheduler launches every group's copy-in during
            # backward and the transient f32 state OOMs; chained,
            # offload_depth groups' f32 (p, m, v) are in HBM at a time
            # and copy-in of group k overlaps update k-1 and copy-out of
            # group k-depth on the full-duplex link.
            chain = [loss] * self.offload_depth
            any_offload = offload_p or offload

            def barriered(p, g, s):
                # serialize per-group host fetches whenever ANY state is
                # host-resident — with only the optimizer offloaded the
                # unconstrained scheduler would fetch every group's
                # moments during backward and OOM on the f32 update
                # transients (hit at 2.7B moment-offload)
                if not any_offload:
                    return p, g, s
                (p, g, _), s = jax.lax.optimization_barrier(
                    ((p, g, chain.pop(0)), s))
                return p, g, s

            new_blk, new_blk_opt = {}, {}
            for sfx in block_params:
                p, g, s = barriered(block_params[sfx], g_blk[sfx],
                                    block_opt[sfx])
                np_, ns = upd2(p, g, s, self.block_opt_specs[sfx],
                               lr, step_no, lr_block[sfx], wd_block[sfx],
                               pspec=self.block_specs[sfx], stacked=True)
                new_blk[sfx] = np_
                new_blk_opt[sfx] = ns
                if any_offload:
                    chain.append(np_)
            new_oth, new_oth_opt = [], []
            for p, g, s, sspec, pspec, plr, wd in zip(
                    other_params, g_oth, other_opt, self.other_opt_specs,
                    self.other_specs, lr_other, wd_other):
                p, g, s = barriered(p, g, s)
                np_, ns = upd2(p, g, s, sspec, lr, step_no, plr, wd,
                               pspec=pspec)
                new_oth.append(np_)
                new_oth_opt.append(ns)
                if any_offload:
                    chain.append(np_)
            return loss, new_blk, new_oth, new_blk_opt, new_oth_opt

        ns = lambda spec: NamedSharding(mesh, spec)
        ons = self._opt_ns          # pinned_host when offloading
        pns = self._param_ns        # pinned_host when offload_params
        blk_sh = {k: pns(v) for k, v in self.block_specs.items()}
        oth_sh = [pns(s) for s in self.other_specs]
        blk_opt_sh = {k: {kk: ons(vv) for kk, vv in v.items()}
                      for k, v in self.block_opt_specs.items()}
        oth_opt_sh = [{kk: ons(vv) for kk, vv in d.items()}
                      for d in self.other_opt_specs]
        sp = mesh.shape.get("sp", 1)

        def batch_spec(ndim):
            if ndim >= 2 and sp > 1:
                return P("dp", "sp")
            return P("dp") if ndim >= 1 else P()

        self._batch_spec = batch_spec
        self._step_fn = jax.jit(
            step_fn,
            in_shardings=(blk_sh, oth_sh, blk_opt_sh, oth_opt_sh,
                          None, None, None, None),
            out_shardings=(ns(P()), blk_sh, oth_sh, blk_opt_sh, oth_opt_sh),
            donate_argnums=(0, 1, 2, 3))
        self._n_batch_args = n_batch_args

    def step(self, *batch) -> jax.Array:
        from ..core import rng as rng_mod

        if self.abstract:
            raise RuntimeError(
                "This trainer was built from a LazyGuard (abstract) model "
                "— it can plan (memory_analysis / aot_lower) but not "
                "execute. Materialize the model (framework.lazy."
                "materialize) and rebuild the trainer to train.")
        if self._step_fn is None or self._n_batch_args != len(batch):
            self._build(len(batch))
        self._step += 1
        vs = []
        for b in batch:
            v = b._value if isinstance(b, Tensor) else jnp.asarray(b)
            vs.append(jax.device_put(v, NamedSharding(
                self.mesh, self._batch_spec(v.ndim))))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, self.block_vals, self.other_vals, self.block_opt, \
            self.other_opt = self._step_fn(
                self.block_vals, self.other_vals, self.block_opt,
                self.other_opt, tuple(vs), lr,
                jnp.asarray(self._step, jnp.int32), rng_mod.next_key())
        self.optimizer._global_step = self._step
        return loss

    __call__ = step

    def memory_analysis(self, *batch):
        """Compiled-memory report of the train step (bytes), from XLA's
        buffer assignment — the only truthful HBM accounting under a
        remote-device tunnel where ``Device.memory_stats()`` is None.
        ``peak ≈ arguments − aliased + temps`` (donated state re-uses its
        argument buffers; offloaded state is host-resident and excluded
        from the HBM argument total by XLA's per-space accounting)."""
        ma = self.aot_compile(*batch).memory_analysis()
        if ma is None:
            return None
        out = {k: int(getattr(ma, k)) for k in
               ("argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes") if hasattr(ma, k)}
        if {"argument_size_in_bytes", "temp_size_in_bytes",
                "alias_size_in_bytes"} <= out.keys():
            out["peak_bytes_est"] = (out["argument_size_in_bytes"]
                                     - out["alias_size_in_bytes"]
                                     + out["temp_size_in_bytes"])
        if self.offload_params or self.offload_optimizer:
            # split HBM vs host arguments (r3 "cannot split" note closed):
            # XLA's argument total folds pinned_host args in, but WE know
            # exactly which state the trainer placed host-side — subtract
            # its bytes to get the HBM-resident argument set.
            host = 0

            def nbytes(v):
                return int(np.prod(v.shape)) * jnp.dtype(v.dtype).itemsize

            if self.offload_params:
                host += sum(nbytes(v) for v in self.block_vals.values())
                host += sum(nbytes(v) for v in self.other_vals)
            if self.offload_optimizer:
                host += sum(nbytes(v) for s in self.block_opt.values()
                            for v in s.values())
                host += sum(nbytes(v) for s in self.other_opt
                            for v in s.values())
            out["host_resident_argument_bytes"] = host
            out["hbm_argument_bytes"] = max(
                out.get("argument_size_in_bytes", 0) - host, 0)
            if "peak_bytes_est" in out:
                out["hbm_peak_bytes_est"] = max(
                    out["peak_bytes_est"] - host, 0)
        return out

    def aot_lower(self, *batch):
        """AOT-lower the train step without executing anything. ``batch``
        entries may be Tensors, arrays, or ``jax.ShapeDtypeStruct``s
        (required in abstract/LazyGuard mode — nothing is materialized
        anywhere in that path)."""
        if self._step_fn is None or self._n_batch_args != len(batch):
            self._build(len(batch))
        vs = []
        for b in batch:
            if isinstance(b, jax.ShapeDtypeStruct):
                vs.append(jax.ShapeDtypeStruct(
                    tuple(b.shape), b.dtype, sharding=NamedSharding(
                        self.mesh, self._batch_spec(len(b.shape)))))
            else:
                v = b._value if isinstance(b, Tensor) else jnp.asarray(b)
                vs.append(jax.device_put(v, NamedSharding(
                    self.mesh, self._batch_spec(v.ndim))))
        # constant key: only avals matter for lowering, and a diagnostic
        # must not advance the training RNG stream
        return self._step_fn.lower(
            self.block_vals, self.other_vals, self.block_opt,
            self.other_opt, tuple(vs),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.uint32))

    def aot_compile(self, *batch):
        return self.aot_lower(*batch).compile()

    # -- sharded checkpoint integration (distributed/checkpoint.py) -------
    def device_state(self):
        """The trainer's on-device state as one pytree of sharded arrays
        (params + optimizer state), for distributed.checkpoint.save."""
        return {"block": dict(self.block_vals),
                "other": list(self.other_vals),
                "block_opt": {k: dict(v) for k, v in self.block_opt.items()},
                "other_opt": [dict(d) for d in self.other_opt]}

    def load_device_state(self, st, step: Optional[int] = None):
        """Inverse of device_state (resume-exact: same values, shardings)."""
        self.block_vals = dict(st["block"])
        self.other_vals = list(st["other"])
        self.block_opt = {k: dict(v) for k, v in st["block_opt"].items()}
        self.other_opt = [dict(d) for d in st["other_opt"]]
        if step is not None:
            self._step = int(step)
            self.optimizer._global_step = int(step)

    def sync_to_layer(self):
        """Unstack device state (params AND optimizer accumulators) back
        into the eager model/optimizer, so state_dict/checkpoints see the
        trained values."""
        L = self.n_layers

        def unstack(a):
            if getattr(a.sharding, "memory_kind", None) == "pinned_host":
                a = jax.device_put(
                    a, NamedSharding(self.mesh, a.sharding.spec))
            if self.v == 1:
                return a.reshape((L,) + tuple(a.shape[2:]))
            # invert the circular assignment: [pp, v, lps_v, ...] -> [L,...]
            return jnp.swapaxes(a, 0, 1).reshape((L,) + tuple(a.shape[3:]))

        for sfx_i, sfx in enumerate(self.block_suffixes):
            stacked = self.block_vals[sfx]
            flat = unstack(stacked)
            opt_flat = {k: unstack(v)
                        for k, v in self.block_opt[sfx].items()}
            for i in range(L):
                t = self._per_block_tensors[i][sfx_i]
                t._value = flat[i]
                self.optimizer._accumulators[id(t)] = {
                    k: v[i] for k, v in opt_flat.items()}
        for n, v, s in zip(self.other_names, self.other_vals,
                           self.other_opt):
            t = self._name2tensor[n]
            if getattr(v.sharding, "memory_kind", None) == "pinned_host":
                v = jax.device_put(
                    v, NamedSharding(self.mesh, v.sharding.spec))
            t._value = v
            self.optimizer._accumulators[id(t)] = s
        return self.model
