"""Tensor-parallel layers.

First-class TP (SURVEY.md §2.2: the reference only has the Megatron-style
`paddle.distributed.split` seed, collective.py:566,492,526 — full TP must be
first-class here for the GPT north star).

Design: layers carry a PartitionSpec per parameter in ``param_shardings``.
In the pjit path the strategy compiler reads these to build NamedShardings —
GSPMD then inserts the all-reduces the reference wrote by hand
(_parallel_linear's c_allreduce after row-parallel matmul). Eagerly (single
process) they behave exactly like their dense counterparts, so tests run
anywhere.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer

TP_AXIS = "tp"


class ColumnParallelLinear(Layer):
    """W [in, out] sharded on out (column). Forward output is sharded on the
    feature dim; gather_output=True adds an all-gather (GSPMD inserts it
    when the output spec demands replication)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, gather_output=True,
                 name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True) if has_bias else None
        self.param_shardings = {"weight": P(None, TP_AXIS),
                                "bias": P(TP_AXIS)}
        self.output_sharding = P() if gather_output else \
            P(None, None, TP_AXIS)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """W [in, out] sharded on in (row); input expected feature-sharded; the
    partial products are psum'd (GSPMD all-reduce)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, input_is_parallel=False,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True) if has_bias else None
        self.param_shardings = {"weight": P(TP_AXIS, None), "bias": P()}
        self.output_sharding = P()

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over vocab
    (reference: collective.py:492 _parallel_embedding)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.param_shardings = {"weight": P(TP_AXIS, None)}

    def forward(self, x):
        return F.embedding(x, self.weight)


ParallelEmbedding = VocabParallelEmbedding


class ParallelCrossEntropy(Layer):
    """Loss over vocab-sharded logits; GSPMD handles the partial max/sum
    reductions across the tp axis."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, logits, labels):
        return F.cross_entropy(logits, labels, reduction="mean")
