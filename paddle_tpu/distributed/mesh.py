"""Device-mesh management.

TPU-native replacement for the reference's ring registry
(reference: platform/collective_helper.h:52-110 NCCLCommContext keyed by
ring_id). Rings become named mesh axes; the 'comm backend' is XLA's
collective lowering over ICI/DCN (SURVEY.md §5).

Axis convention (north-star GPT hybrid parallel, SURVEY §7):
  dp — data parallel        pp — pipeline stages
  tp — tensor/model parallel sp — sequence/context parallel
  ep — expert parallel
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_current_mesh: Optional[Mesh] = None

P = PartitionSpec


def create_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None
                ) -> Mesh:
    """Build a Mesh from {'dp': 2, 'tp': 4, ...}. Axis sizes must multiply to
    the device count; axes of size 1 are kept (so sharding specs stay
    stable across configs)."""
    devs = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = [int(axes[n]) for n in names]
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh axes {axes} require {total} devices, have {len(devs)}")
    arr = np.asarray(devs).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def set_mesh(mesh: Mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


def init_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    return set_mesh(create_mesh(axes, devices))


def sharding(*spec, mesh: Optional[Mesh] = None) -> NamedSharding:
    m = mesh or _current_mesh
    if m is None:
        raise RuntimeError("No mesh set; call init_mesh first.")
    return NamedSharding(m, PartitionSpec(*spec))


def axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    m = mesh or _current_mesh
    if m is None or name not in m.axis_names:
        return 1
    return m.shape[name]
