"""Pipeline activation-memory measurement (VERDICT r2 item 3).

Question: does the whole-schedule-AD pipeline (distributed/pipeline.py —
one lax.scan over ticks, differentiated end to end) retain activation
memory that grows with n_micro (GPipe-like), or does remat bound it?

Method: AOT-compile the hybrid trainer's full train step for a grid of
(pp, n_micro, remat) on a virtual CPU mesh and read the XLA executable's
`memory_analysis().temp_size_in_bytes` — the compiler's own peak
temp-buffer accounting (the same quantity a real TPU HBM budget sees,
modulo backend constants). The reference's comparable number is the
per-microbatch scope pool in SectionWorker (section_worker.cc:34, one
scope per microbatch held until backward — memory strictly ∝ n_micro).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PALLAS_AXON_POOL_IPS= python benchmarks/pipeline_memory.py

MEASURED (2026-07-30, GPT h128 L8 s128 batch16, this harness):
  remat=False pp=2: temp 315→181 MB as n_micro 2→16 (slope −8 MB/micro)
  remat=False pp=4: temp 161→110 MB as n_micro 4→16
  remat=True  pp=2: temp 34.4→27.5 MB, flat (slope −0.4 MB/micro)
  remat=True  pp=4: temp 25.4→24.1 MB, flat
Conclusion: at fixed GLOBAL batch, peak activation memory does NOT grow
with n_micro — per-tick residuals scale as n_ticks × microbatch ≈ const
× batch, and jax.checkpoint bounds the whole schedule at ~flat memory
(11× below no-remat). The GPipe-style blowup VERDICT r2 item 3 feared
(retained per-tick buffers ∝ n_micro) does not occur; a 1F1B
memory-bounded schedule is a latency optimization here, not a memory
necessity. (Growing the global batch WITH n_micro grows memory
linearly, as any schedule that materializes all microbatch outputs for
the loss head must.)
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(pp: int, n_micro: int, remat: bool, batch: int = 16,
            seq: int = 128, hidden: int = 128, layers: int = 8):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng as rng_mod
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.hybrid import HybridPipelineTrainer
    from paddle_tpu.distributed.strategy_compiler import \
        build_mesh_from_strategy
    from paddle_tpu.models import GPT, GPTConfig

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=hidden, num_layers=layers,
                    num_heads=4, max_seq_len=seq)
    net = GPT(cfg)
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": pp}
    s.pipeline = pp > 1
    s.recompute = remat
    mesh = build_mesh_from_strategy(s, jax.devices()[:pp])
    opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
    tr = HybridPipelineTrainer(net, opt, s, mesh, n_micro=n_micro)
    tr._build(1)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, 512, (batch, seq)).astype(np.int32))
    lowered = tr._step_fn.lower(
        tr.block_vals, tr.other_vals, tr.block_opt, tr.other_opt,
        (tokens,), jnp.asarray(1e-3, jnp.float32),
        jnp.asarray(1, jnp.int32), rng_mod.next_key())
    ma = lowered.compile().memory_analysis()
    return {"pp": pp, "n_micro": n_micro, "remat": remat,
            "temp_mb": round(ma.temp_size_in_bytes / 2**20, 1),
            "arg_mb": round(ma.argument_size_in_bytes / 2**20, 1)}


def main():
    rows = []
    for remat in (False, True):
        for pp, micros in ((2, (2, 4, 8, 16)), (4, (4, 8, 16))):
            for nm in micros:
                r = measure(pp, nm, remat)
                rows.append(r)
                print(json.dumps(r), flush=True)
    # growth verdict: fit temp ~ a + b*n_micro per (pp, remat) series
    print("\n-- growth per extra microbatch (MB) --")
    for remat in (False, True):
        for pp in (2, 4):
            series = [(r["n_micro"], r["temp_mb"]) for r in rows
                      if r["pp"] == pp and r["remat"] == remat]
            if len(series) >= 2:
                xs, ys = zip(*series)
                b = np.polyfit(xs, ys, 1)[0]
                print(json.dumps({"pp": pp, "remat": remat,
                                  "mb_per_microbatch": round(float(b), 2),
                                  "series": series}))


if __name__ == "__main__":
    main()
