"""GPT-3 13B hybrid-parallel memory/compile plan — the north-star proof.

BASELINE.md: the metric is tokens/sec/chip + MFU on GPT-3 1.3B-13B; the
north star is 13B hybrid-parallel (TP×PP×sharding) on v5p with ≥45% MFU.
This script proves the 13B end *compiles and fits*: it

  1. builds ``GPTConfig.gpt3_13b()`` under ``paddle.LazyGuard`` — every
     parameter is a ShapeDtypeStruct, so planning a 156 GB-state model
     materializes nothing on host or device;
  2. AOT-lowers + compiles the FULL hybrid train step (tp×pp×dp(ZeRO),
     remat, bf16 param/moment storage, fused flash attention, layer scan)
     through ``HybridPipelineTrainer.aot_compile`` on a virtual 16-device
     mesh for three candidate factorizations;
  3. records XLA's per-chip buffer-assignment accounting
     (``memory_analysis``: arguments − aliased + temps ≈ peak HBM) against
     the 95 GB v5p budget into ``BENCH_13B_PLAN.json``;
  4. (--dryrun) materializes a tiny-hidden, SAME-depth (40-layer) variant
     of the chosen plan and runs real steps, asserting the loss is finite
     and descending — the schedule/sharding path is executed, not only
     compiled.

Run on the CPU backend (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=16 \
  python benchmarks/plan_13b.py [--dryrun]

Honesty notes recorded in the sidecar: the lowering is XLA:CPU SPMD (the
only backend this 1-chip environment can factorize 16 ways); TPU layouts
(8×128 tiling) can pad differently, and the CPU path promotes some bf16
boundaries to f32 (pipeline.py CPU workaround), which *overstates*
activation bytes — the budget check is conservative in that direction.
Reference-scale knobs this corresponds to:
/root/reference/paddle/fluid/framework/distributed_strategy.proto:25-35
(RecomputeConfig/ShardingConfig) — here they are strategy fields compiled
into one pjit program (SURVEY §7).
"""
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5P_HBM_GB = 95.0
SEQ = 2048
GLOBAL_BATCH = 32          # sequences per step (fill-drain over n_micro)


def rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def plan_one_v5p(cfg, plan):
    """The definitive lowering: AOT-compile against a REAL v5p 2x4x2
    topology (jax.experimental.topologies — the actual TPU compiler and
    layouts, bf16 collectives, no CPU promotions). remat_policy='dots'
    because the pip-bundled libtpu miscompiles full-remat+scan flash
    ('Bad lhs type', see tests/test_tpu_lowering.py) — selective remat
    is the production bench config anyway."""
    import os
    import time as _t

    import jax
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5p:2x4x2")
    os.environ["PADDLE_TPU_TARGET_PLATFORM"] = "tpu"
    try:
        t0 = _t.time()
        plan = dict(plan, remat_policy="dots")
        _, _, trainer = build_trainer(cfg, plan, devices=topo.devices)
        batch = jax.ShapeDtypeStruct((GLOBAL_BATCH, SEQ), np.int32)
        ma = trainer.aot_compile(batch).memory_analysis()
        out = dict(plan)
        out["compile_s"] = round(_t.time() - t0, 1)
        out["host_peak_rss_gb"] = round(rss_gb(), 2)
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes"):
            out[k] = int(getattr(ma, k))
        peak = (out["argument_size_in_bytes"] - out["alias_size_in_bytes"]
                + out["temp_size_in_bytes"])
        out["peak_bytes_per_chip"] = int(peak)
        out["peak_gb_per_chip"] = round(peak / 1e9, 2)
        out["fits_v5p_95gb"] = bool(peak / 1e9 <= V5P_HBM_GB)
        out["hbm_headroom_gb"] = round(V5P_HBM_GB - peak / 1e9, 2)
        return out
    finally:
        del os.environ["PADDLE_TPU_TARGET_PLATFORM"]


def build_trainer(cfg, plan, abstract=True, devices=None):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.distributed_strategy import \
        DistributedStrategy
    from paddle_tpu.distributed.hybrid import HybridPipelineTrainer
    from paddle_tpu.models.gpt import GPT

    strat = DistributedStrategy()
    strat.amp = True
    strat.recompute = True
    strat.hybrid_configs = {"dp_degree": plan["dp"],
                            "mp_degree": plan["tp"],
                            "pp_degree": plan["pp"]}
    if plan.get("zero", 0):
        strat.sharding = True
        strat.sharding_configs = {"sharding_stage": plan["zero"]}
    if abstract:
        with paddle.LazyGuard():
            model = GPT(cfg)
    else:
        model = GPT(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    mesh = None
    if devices is not None:
        from paddle_tpu.distributed.strategy_compiler import \
            build_mesh_from_strategy

        n = plan["dp"] * plan["tp"] * plan["pp"]
        mesh = build_mesh_from_strategy(strat, np.array(devices)[:n])
    trainer = HybridPipelineTrainer(
        model, opt, strategy=strat, mesh=mesh, n_micro=plan["n_micro"],
        param_dtype="bfloat16", moment_dtype="bfloat16",
        remat_policy=plan.get("remat_policy"))
    return model, opt, trainer


def plan_one(cfg, plan):
    import jax
    t0 = time.time()
    _, _, trainer = build_trainer(cfg, plan)
    batch = jax.ShapeDtypeStruct((GLOBAL_BATCH, SEQ), np.int32)
    compiled = trainer.aot_compile(batch)
    ma = compiled.memory_analysis()
    out = dict(plan)
    out["compile_s"] = round(time.time() - t0, 1)
    out["host_peak_rss_gb"] = round(rss_gb(), 2)
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes"):
        out[k] = int(getattr(ma, k))
    peak = (out["argument_size_in_bytes"] - out["alias_size_in_bytes"]
            + out["temp_size_in_bytes"])
    out["peak_bytes_per_chip"] = int(peak)
    out["peak_gb_per_chip"] = round(peak / 1e9, 2)
    out["fits_v5p_95gb"] = bool(peak / 1e9 <= V5P_HBM_GB)
    out["hbm_headroom_gb"] = round(V5P_HBM_GB - peak / 1e9, 2)
    return out


def main():
    import jax
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig.gpt3_13b()
    n_dev = len(jax.devices())
    assert n_dev >= 16, f"need 16 virtual devices, got {n_dev}"

    plans = [
        # tp inside the attention/ffn shards the big matmuls (MXU-friendly
        # 5120/8=640 cols); pp=2 keeps bubble small at n_micro=8
        {"name": "A_tp8_pp2", "tp": 8, "pp": 2, "dp": 1, "zero": 0,
         "n_micro": 8},
        # deeper pipeline, narrower tp: less tp collective traffic,
        # bigger bubble; 40/4=10 layers per stage
        {"name": "B_tp4_pp4", "tp": 4, "pp": 4, "dp": 1, "zero": 0,
         "n_micro": 16},
        # dp=2 with ZeRO-2: moments sharded over dp — the
        # sharding-stage2 leg of the north-star config
        {"name": "C_tp4_pp2_dp2_zero2", "tp": 4, "pp": 2, "dp": 2,
         "zero": 2, "n_micro": 8},
    ]

    results = {"model": "gpt3_13b",
               "hidden": cfg.hidden_size, "layers": cfg.num_layers,
               "heads": cfg.num_heads, "seq": SEQ,
               "vocab": cfg.vocab_size,
               "params_b": round(cfg.num_params() / 1e9, 2),
               "global_batch": GLOBAL_BATCH,
               "n_virtual_devices": n_dev,
               "budget_gb_per_chip": V5P_HBM_GB,
               "storage": "bf16 params + bf16 AdamW moments, f32 update "
                          "math (r3-validated: LOSSCURVE_r03 0.17% rel)",
               "lowering_backend": jax.default_backend(),
               "notes": [
                   "abstract LazyGuard init: zero parameter bytes "
                   "materialized (see host_peak_rss_gb per plan)",
                   "XLA:CPU SPMD lowering; TPU 8x128 layouts may pad "
                   "differently; CPU f32 boundary promotions overstate "
                   "activation bytes (conservative for the budget check)",
               ],
               "plans": []}

    for plan in plans:
        print(f"--- planning {plan['name']} ...", flush=True)
        try:
            r = plan_one(cfg, plan)
        except Exception as e:  # record failures honestly
            r = dict(plan)
            r["error"] = f"{type(e).__name__}: {e}"[:500]
        results["plans"].append(r)
        print(json.dumps(r), flush=True)

    # definitive stage: the REAL v5p compiler + layouts (available
    # offline via jax.experimental.topologies) — the CPU plans above are
    # kept as the comparison proxy
    results["plans_v5p_true_lowering"] = []
    for plan in plans:
        print(f"--- v5p-true lowering {plan['name']} ...", flush=True)
        try:
            r = plan_one_v5p(cfg, plan)
        except Exception as e:
            r = dict(plan)
            r["error"] = f"{type(e).__name__}: {e}"[:500]
        results["plans_v5p_true_lowering"].append(r)
        print(json.dumps(r), flush=True)

    pool = [r for r in results["plans_v5p_true_lowering"]
            if r.get("fits_v5p_95gb")] or \
        [r for r in results["plans"] if r.get("fits_v5p_95gb")]
    if pool:
        chosen = min(pool, key=lambda r: r["peak_bytes_per_chip"])
        results["chosen"] = chosen["name"]
        results["chosen_rationale"] = (
            "chosen from the v5p TRUE lowerings when available (real TPU "
            "layouts); all fitting plans are throughput-equivalent until "
            "measured on hardware — lowest per-chip peak wins (most "
            "activation headroom to raise n_micro/batch toward the MFU "
            "target)")

    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_13B_PLAN.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", out_path)

    if "--dryrun" in sys.argv:
        dryrun(results)


def dryrun(results):
    """Tiny-hidden, full-depth (40-layer) variant of the chosen plan,
    actually executed: 3 steps, loss finite and descending."""
    import jax
    from paddle_tpu.models.gpt import GPTConfig

    name = results.get("chosen", "A_tp8_pp2")
    plan = next(p for p in results["plans"] if p["name"] == name)
    cfg = GPTConfig(hidden_size=128, num_layers=40, num_heads=8,
                    max_seq_len=128, vocab_size=512)
    model, opt, trainer = build_trainer(cfg, plan, abstract=False)
    rng = np.random.RandomState(0)
    bsz = plan["n_micro"] * plan["dp"]
    tok = rng.randint(0, cfg.vocab_size, (bsz, 128)).astype(np.int32)
    losses = [float(trainer.step(tok)) for _ in range(3)]
    print("dryrun losses:", losses)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"loss not descending: {losses}"
    results["dryrun_40layer_tiny"] = {
        "plan": name, "hidden": cfg.hidden_size, "layers": cfg.num_layers,
        "losses": [round(l, 4) for l in losses], "descending": True}
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_13B_PLAN.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print("dryrun green; sidecar updated")


if __name__ == "__main__":
    main()
