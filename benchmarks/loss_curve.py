"""Loss-curve parity artifact: GPT-125M, fixed seed, bf16-vs-f32 delta.

The BASELINE north-star has a "loss-curve parity with the A100/NCCL
baseline" clause. The reference baseline is unobtainable here (no CUDA
hardware, and the reference publishes no curves), so parity is evidenced
the way the reference's own AMP work does (reference
python/paddle/fluid/contrib/mixed_precision/decorator.py: fp16 training
must match fp32 convergence): train the SAME fixed-seed model/data twice —

  f32     : pure f32 compute, f32 AdamW state
  bf16    : amp bf16 compute + f32 master state (the framework's default
            mixed-precision path, amp/)
  bf16s   : amp + bf16 master/moment STORAGE (the 1.3B headline's memory
            layout, hybrid.py param_dtype/moment_dtype)

and record every step's loss + the final-loss relative delta. Run on the
TPU chip:  python benchmarks/loss_curve.py [steps] [out.json]
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_curve(mode: str, steps: int, seed: int = 17):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.hybrid import HybridPipelineTrainer
    from paddle_tpu.distributed.mesh import create_mesh
    from paddle_tpu.models import GPT, GPTConfig

    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=32768, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=512)
    model = GPT(cfg)
    opt = paddle.optimizer.AdamW(
        6e-4, parameters=model.parameters(), weight_decay=0.01)
    s = DistributedStrategy()
    s.amp = mode != "f32"
    mesh = create_mesh({"dp": 1, "pp": 1, "tp": 1, "sp": 1},
                       jax.devices()[:1])
    kw = {}
    if mode == "bf16s":
        kw = dict(param_dtype="bfloat16", moment_dtype="bfloat16")
    tr = HybridPipelineTrainer(model, opt, s, mesh, n_micro=1, **kw)

    # fixed-seed synthetic LM stream with learnable structure (Zipfian
    # unigram + bigram continuation), deterministic across configs
    rng = np.random.RandomState(123)
    freq = 1.0 / np.arange(1, cfg.vocab_size + 1) ** 1.1
    freq /= freq.sum()
    next_tok = rng.permutation(cfg.vocab_size)

    def make_batch(i):
        r = np.random.RandomState(1000 + i)
        base = r.choice(cfg.vocab_size, size=(8, 512), p=freq)
        # half the positions continue deterministically: learnable signal
        cont = next_tok[base[:, :-1]]
        mask = r.rand(8, 511) < 0.5
        base[:, 1:] = np.where(mask, cont, base[:, 1:])
        return base.astype(np.int32)

    losses = []
    for i in range(steps):
        loss = tr.step(make_batch(i))
        losses.append(float(np.asarray(loss)))
    return losses


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 300
    out_path = sys.argv[2] if len(sys.argv) > 2 else "LOSSCURVE_r03.json"
    t0 = time.perf_counter()
    curves = {}
    for mode in ("f32", "bf16", "bf16s"):
        t = time.perf_counter()
        curves[mode] = run_curve(mode, steps)
        print(f"{mode}: final {curves[mode][-1]:.4f} "
              f"({time.perf_counter() - t:.0f}s)", flush=True)
    f32, bf16, bf16s = (curves[m][-1] for m in ("f32", "bf16", "bf16s"))
    out = {
        "model": "gpt_125m", "steps": steps, "batch": 8, "seq": 512,
        "final_loss": {"f32": f32, "bf16": bf16, "bf16s": bf16s},
        "rel_delta_bf16_vs_f32": abs(bf16 - f32) / f32,
        "rel_delta_bf16storage_vs_f32": abs(bf16s - f32) / f32,
        "curves_every_10": {m: c[::10] for m, c in curves.items()},
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items()
                      if k != "curves_every_10"}))


if __name__ == "__main__":
    main()
