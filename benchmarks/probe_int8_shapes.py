"""Shape-sensitivity probe for int8 serving (r5, VERDICT r4 next #2).

Runs bench.bench_predictor_int8 at alternative MLP shapes to test
whether the int8/bf16 predictor ratio rises with arithmetic intensity.
Measured on the one v5e (2026-07-31, recorded in the computebound
config's note):

  - 4096x16384 @ batch 4096: bf16 9.15 ms, int8 6.51 ms -> 1.41x
    (int8 dots ~46% of 394T int8 peak; bf16 ~53% of 197T)
  - 5120x20480 @ batch 2048 (13B FFN dims): bf16 9.73 ms, int8
    7.61 ms -> 1.28x (int8 drops to ~29% of peak, bf16 ~45%)

Conclusion: the ratio is bounded by XLA's int8 matmul efficiency,
which is SHAPE-dependent and peaks near the 4096 shape — not by the
framework's deploy graph (raw-kernel ratio 1.72-1.75x at the 4096
shape; the fused Mosaic kernel alternative measured slower still,
ops/int8_matmul.py docstring).

Usage: PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/probe_int8_shapes.py
"""
import json
import sys

sys.path.insert(0, ".")


def main():
    import paddle_tpu as paddle

    import bench

    for d, h, batch in ((4096, 16384, 4096), (5120, 20480, 2048)):
        out = bench.bench_predictor_int8(paddle, steps=20, batch=batch,
                                         include_f32=False, d=d, h=h)
        out.pop("note", None)
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
