"""Measure GPipe vs interleaved pipeline schedules on the 8-device CPU
mesh (VERDICT r1 item 6: step-time win at pp>=2, n_micro>=4).

Run: python benchmarks/pipeline_bubble.py
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.hybrid import HybridPipelineTrainer
    from paddle_tpu.distributed.strategy_compiler import \
        build_mesh_from_strategy
    from paddle_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=8,
                    num_heads=4, max_seq_len=128)
    toks = np.random.RandomState(0).randint(
        0, 512, (16, 128)).astype(np.int32)

    def run(v, n_micro=8, steps=6):
        paddle.seed(1)
        net = GPT(cfg)
        opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
        s = DistributedStrategy()
        s.hybrid_configs = {"pp_degree": 4, "dp_degree": 2}
        mesh = build_mesh_from_strategy(s)
        tr = HybridPipelineTrainer(net, opt, s, mesh, n_micro=n_micro,
                                   v_virtual=v)
        float(np.asarray(tr.step(toks)))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = tr.step(toks)
        float(np.asarray(loss))
        return (time.perf_counter() - t0) / steps * 1e3

    t1 = run(1)
    t2 = run(2)
    print(f"pp=4 n_micro=8: gpipe {t1:.1f} ms | interleaved v=2 {t2:.1f} ms "
          f"| win {100 * (1 - t2 / t1):.1f}%")
    print(f"theoretical bubble: gpipe {3 / 11:.3f} vs v=2 {3 / 19:.3f}")


if __name__ == "__main__":
    main()
