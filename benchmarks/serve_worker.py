"""One rank of the multi-host serving bench (serve_bench --hosts N).

Launched by ``benchmarks/serve_bench.py bench_multihost`` through
tools/mp_mesh.py. Reads a JSON cell config, brings up the mesh (world
1 skips jax.distributed entirely), builds a DisaggServer over its
shard, WARMS the compiled programs off the clock, then drives the
shared Poisson/burst trace and writes per-rank stats for the driver to
aggregate.

argv: config.json rank_out_dir
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mp_mesh  # noqa: E402


def main():
    with open(sys.argv[1]) as f:
        cfg = json.load(f)
    out_dir = sys.argv[2]
    world = int(cfg["world"])
    env_only = bool(cfg.get("env_only"))
    if world > 1 and env_only:
        # elastic cells (ISSUE 17): no jax.distributed — its fatal
        # poller would abort the survivors the moment the die_rank
        # exits; the mesh's own board is the only control plane
        rank, w = mp_mesh.init_env_only()
        assert w == world
    elif world > 1:
        rank, w = mp_mesh.init()
        assert w == world
    else:
        rank = 0
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.serving import (DisaggServer, MeshSpec,
                                    ServingConfig)

    paddle.seed(0)
    m = cfg["model"]
    net = GPT(GPTConfig(vocab_size=m["vocab"], hidden_size=m["hidden"],
                        num_layers=m["layers"], num_heads=m["heads"],
                        max_seq_len=m["max_seq_len"],
                        initializer_range=0.2))
    net.eval()

    rng = np.random.RandomState(cfg["seed"])
    trace = []
    t = 0.0
    tn = cfg.get("tenants")
    if tn:
        # prefix-economy cells (ISSUE 18): T tenants, each with its
        # own system prompt, interleaved round-robin — every request
        # is <tenant system prefix> + <unique suffix>. RNG call order
        # (systems first, then one suffix per request) is the contract
        # the driver replays to compute dense-reference outputs.
        systems = [rng.randint(0, 128, (int(tn["sys_len"]),))
                   .astype(np.int32) for _ in range(int(tn["n"]))]
        # optional skew pattern (e.g. [0, 1, 0, 2]: tenant 0 is the
        # hot one) — load on the hot tenant's affine rank is what
        # forces spill + hot-chain migration
        pat = tn.get("pattern") or list(range(len(systems)))
        for i in range(cfg["n_requests"]):
            t += float(rng.exponential(1.0 / cfg["rate"]))
            sfx = rng.randint(0, 128, (int(tn["sfx_len"]),)) \
                .astype(np.int32)
            trace.append((t, np.concatenate(
                [systems[pat[i % len(pat)]], sfx]),
                int(cfg["max_new"])))
    else:
        for i in range(cfg["n_requests"]):
            t += float(rng.exponential(1.0 / cfg["rate"]))
            ln = cfg["prompt_lens"][i % len(cfg["prompt_lens"])]
            trace.append((t, rng.randint(0, 128, (ln,))
                          .astype(np.int32), int(cfg["max_new"])))

    scfg = ServingConfig(**cfg["engine"])
    srv = DisaggServer(
        net, scfg, MeshSpec(rank, world,
                            prefill_ranks=tuple(cfg["prefill_ranks"])),
        cfg["shared_dir"], lease_s=float(cfg.get("lease_s", 5.0)),
        long_prompt_threshold=cfg.get("long_prompt_threshold"),
        prefix_routing=bool(cfg.get("prefix_routing")),
        prefix_publish_s=float(cfg.get("prefix_publish_s", 0.5)))

    # ---- warm every compiled program OFF the measured clock: the
    # tick (via a held prefill), the export read AND the import
    # writer (every rank warms the full handoff round-trip on itself
    # — a decode rank's first real import must not pay a compile) ----
    eng = srv.engine
    warm_p = rng.randint(0, 128, (max(cfg["prompt_lens"]),)) \
        .astype(np.int32)
    wr = eng.submit(warm_p, 2, hold_after_prefill=True)
    for _ in range(300):
        eng.step()
        eng.drain(0)
        if wr in eng.held_ready():
            pl = eng.export_held(wr)
            eng.release_exported(wr)
            eng.admit_prefilled(pl)     # warms the import writer
        if all(r is None for r in eng._slot_rid) and not eng._queue:
            break
    eng.drain(0)
    # warm the prefix-migration round trip too (ISSUE 18): the warm
    # request's chain is still indexed — export it through the
    # fixed-shape jitted gather and re-import it (a duplicate chain:
    # its pages bounce straight back to the pool), so a mid-run
    # migration never pays either compile on the measured clock
    mig = eng.export_prefix_chain(warm_p)
    if mig is not None:
        eng.import_prefix_chain(mig)
    eng.pool.drop_prefix_cache()
    eng.reset_results()

    import resource

    from paddle_tpu.profiler import registry as _reg

    # the warm round-trip moved real counters (handoff bytes, chunks,
    # ticks) — zero the registry so the reported stats cover ONLY the
    # measured window; same for the event ring (the per-rank sink
    # below must stream measured-window events only)
    _reg().reset()
    import paddle_tpu.profiler as _profiler

    _profiler.event_log().clear()
    # per-rank sink (ISSUE 14): the driver merges
    # <sink_dir>/rank<K>/ with tools/merge_traces.py into the
    # mesh-wide clock-aligned latency block
    if cfg.get("sink_dir"):
        if world > 1 and env_only:
            # env-protocol ranks have no jax.distributed to detect
            # the rank from (_detect_rank would say 0 on every rank,
            # interleaving one JSONL file) — place each rank's sink
            # explicitly so the merger still sees rank<K>/ dirs
            _profiler.enable_sink(
                os.path.join(cfg["sink_dir"], f"rank{rank}"),
                per_rank_subdir=False, rank=rank, interval_s=10.0)
        else:
            _profiler.enable_sink(cfg["sink_dir"], interval_s=10.0)

    if world > 1 and env_only:
        # file-based warm barrier: there is no coordination service
        with open(os.path.join(out_dir, f"warm.{rank}"), "w") as f:
            f.write("ok\n")
        assert mp_mesh.wait_for_files(
            [os.path.join(out_dir, f"warm.{r}") for r in range(world)],
            timeout_s=300.0)
    elif world > 1:
        mp_mesh.barrier("warm")
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    start_w = time.time()
    pending = list(trace)
    # elastic kill cell (ISSUE 17): this rank dies ABRUPTLY once the
    # clock passes die_after_s AND it holds at least one unserved
    # assigned request — a real corpse with real orphans, not a
    # graceful drain (the holding gate keeps the measurement honest:
    # arrivals still pending at die time guarantee it fires)
    die_at = (float(cfg["die_after_s"])
              if rank == cfg.get("die_rank") else None)
    # end_w stamps the LAST serving progress (tokens/handoffs), not
    # the done-agreement adoption: the completion vote is control
    # plane (rate-limited rounds) and must not pollute the throughput
    # clock the driver aggregates
    end_w = start_w
    last_sig = (-1.0, -1, -1)
    while True:
        now = time.time() - start_w
        while pending and pending[0][0] <= now:
            _, p, mn = pending.pop(0)
            srv.submit(p, mn)
        if die_at is not None and now >= die_at:
            served_now = srv.results()
            if any(d == rank and g not in served_now
                   for g, (_, d) in srv._assignments.items()):
                os._exit(137)    # no close, no stats, no goodbyes
        progressed = srv.step()
        sig = (_reg().counter("serving/tokens_generated").value,
               srv.handoffs_sent, srv.handoffs_recv)
        if sig != last_sig:
            last_sig = sig
            end_w = time.time()
        if srv._done_verdict and not pending:
            break
        if not progressed and not pending:
            time.sleep(0.002)
        if time.time() - start_w > float(cfg.get("timeout_s", 600)):
            raise SystemExit(f"rank {rank}: bench cell never drained")

    from paddle_tpu.profiler import registry

    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    res = srv.results()
    stats = {
        "rank": rank,
        "start_w": start_w,
        "end_w": end_w,
        # this rank's CPU seconds over the measured window (all
        # threads): the driver's parallel-hardware projection divides
        # total tokens by max-per-rank CPU — what N actual cores
        # would approximately realize, which a 1-core container's
        # timeshared WALL clock cannot exhibit
        "cpu_s": round((ru1.ru_utime + ru1.ru_stime)
                       - (ru0.ru_utime + ru0.ru_stime), 4),
        "tokens": int(sum(len(v) for v in res.values())),
        "served": sorted(res),
        "ttft_ms": {str(g): round(v, 3)
                    for g, v in srv.ttfts().items()},
        # handed-off requests' TTFTs are true end-to-end cross-host
        # deltas (ISSUE 14): each carries its clock-alignment bound
        "ttft_unc_ms": {str(g): round(u, 3)
                        for g, u in srv.ttft_uncs().items()},
        "handoffs_sent": srv.handoffs_sent,
        "handoffs_recv": srv.handoffs_recv,
        "handoff_bytes_out": registry().counter(
            "serving/handoff_bytes_out").value,
        "preemptions": registry().counter(
            "serving/preemptions").value,
        "prefill_chunks": registry().counter(
            "serving/prefill_chunks").value,
        "prefix_evictions": registry().counter(
            "cache_share/prefix_evictions").value,
        "ticks": registry().counter("serving/ticks").value,
        # elastic evidence (ISSUE 17): which gids this rank re-served
        # after a peer died, and by which mode — the driver's
        # re-dispatched-tail TTFT inflation cell reads these
        "redispatched": {str(g): m
                         for g, m in srv.redispatched.items()},
        "members": sorted(srv._members),
        # global KV economy evidence (ISSUE 18): per-rank because each
        # rank is its own PROCESS here — the registry split the
        # in-process threaded tests cannot observe. Same shape as
        # write_results' prefix_economy block; present in BOTH arms
        # (the affinity-blind arm still serves local prefix hits, so
        # its hit_tokens are the baseline the speedup is priced
        # against).
        "prefix": {
            "prefix_hit_tokens": int(registry().counter(
                "serving/prefix_hit_tokens").value),
            "remote_hit_tokens": int(registry().counter(
                "serving/prefix_hit_tokens_remote").value),
            "migrations_out": srv.prefix_migrations_out,
            "migrations_in": srv.prefix_migrations_in,
            "migration_bytes_out": srv.prefix_migration_bytes_out,
            "migration_bytes_in": srv.prefix_migration_bytes_in,
            "stale_withdrawals": srv.stale_digest_withdrawals,
            "kv_dtype": str(np.dtype(srv.engine.pool.k.dtype)),
            "published_chains": len(srv._published_chains),
        },
    }
    if cfg.get("return_outputs"):
        # full decoded sequences (prompt + generation), gid-keyed:
        # the driver bitwise-compares them against dense references
        stats["outputs"] = {str(g): [int(x) for x in v]
                            for g, v in res.items()}
    path = os.path.join(out_dir, f"bench.{rank}.json")
    with open(path + ".tmp", "w") as f:
        json.dump(stats, f)
    os.replace(path + ".tmp", path)
    if cfg.get("sink_dir"):
        _profiler.disable_sink()    # final flush BEFORE the hard exit
    srv.close()
    ok = os.path.join(out_dir, f"ok.{rank}")
    if world > 1:
        if rank == 0 and not env_only:
            # rank 0 only hosts a coordination service on the
            # jax.distributed path — env-only ranks exit freely
            mp_mesh.finish_last(ok, [os.path.join(out_dir, f"ok.{r}")
                                     for r in range(1, world)])
        mp_mesh.finish(ok)
    with open(ok, "w") as f:
        f.write("OK\n")


if __name__ == "__main__":
    main()
