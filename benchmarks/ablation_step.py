"""In-trainer ablations to find the missing step time.

Emits ONE JSON line (plus the human-readable prints): per-ablation step
ms AND the profiler's per-phase decomposition of the full config —
fwd/bwd/optim/comm ms and tokens/sec from paddle_tpu.profiler — instead
of bare wall-clock totals. The ablation timing loops themselves run with
the profiler DISABLED (its disabled cost is one bool read per step, so
the numbers stay comparable with earlier rounds).
"""
import json
import os
import sys
import time

import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from bench import profiler_block  # noqa: E402 - the ONE telemetry harness


def step_time(tr, tokens, n=10):
    float(np.asarray(tr.step(tokens)))
    float(np.asarray(tr.step(tokens)))
    t0 = time.perf_counter()
    for _ in range(n):
        loss = tr.step(tokens)
    float(np.asarray(loss))
    return (time.perf_counter() - t0) / n * 1e3


def make(cfg_kw=None, strat_kw=None, n_micro=1):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.hybrid_gpt import GPTHybridTrainer
    from paddle_tpu.distributed.mesh import create_mesh
    from paddle_tpu.models import GPT, GPTConfig

    paddle.seed(0)
    kw = dict(vocab_size=32768, hidden_size=768, num_layers=12,
              num_heads=12, max_seq_len=1024)
    kw.update(cfg_kw or {})
    cfg = GPTConfig(**kw)
    model = GPT(cfg)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    s = DistributedStrategy()
    s.amp = True
    for k, v in (strat_kw or {}).items():
        setattr(s, k, v)
    mesh = create_mesh({"dp": 1, "pp": 1, "tp": 1, "sp": 1},
                       jax.devices()[:1])
    return GPTHybridTrainer(model, opt, s, mesh, n_micro=n_micro), cfg


def main():
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 32768, (8, 1024)).astype(np.int32)
    results = {}

    tr, cfg = make()
    t_full = step_time(tr, tokens)
    print(f"full step: {t_full:.2f} ms")
    results["full"] = {"step_ms": round(t_full, 2)}

    # ablate attention (unfused==flash swap shows reshape overhead instead)
    import paddle_tpu.models.gpt as gptmod

    orig_fwd = gptmod.GPTAttention.forward

    def no_attn(self, x):
        return self.out_proj(self.qkv_proj(x)[..., :x.shape[-1]])

    gptmod.GPTAttention.forward = no_attn
    tr2, _ = make()
    t = step_time(tr2, tokens)
    print(f"no-attention step: {t:.2f} ms (attention total = {t_full - t:.2f})")
    results["no_attention"] = {"step_ms": round(t, 2),
                               "attention_ms": round(t_full - t, 2)}
    gptmod.GPTAttention.forward = orig_fwd

    # ablate loss head: mean instead of fused CE
    from paddle_tpu.distributed import hybrid_gpt as hg
    import paddle_tpu.ops.fused_ce as fce

    orig_ce = fce.fused_linear_cross_entropy_fn
    fce.fused_linear_cross_entropy_fn = \
        lambda x, w, l, **kw: jnp.sum(x.astype(jnp.float32)) * 1e-6 + \
        jnp.sum(w.astype(jnp.float32)) * 1e-9
    tr3, _ = make()
    t = step_time(tr3, tokens)
    print(f"no-CE step: {t:.2f} ms (loss head total = {t_full - t:.2f})")
    results["no_ce"] = {"step_ms": round(t, 2),
                        "loss_head_ms": round(t_full - t, 2)}
    fce.fused_linear_cross_entropy_fn = orig_ce

    # unfused attention for comparison
    tr4, _ = make(cfg_kw={"use_flash_attention": False})
    t = step_time(tr4, tokens)
    print(f"unfused-attention step: {t:.2f} ms")
    results["unfused_attention"] = {"step_ms": round(t, 2)}

    # remat on (cheaper bwd memory, more flops)
    tr5, _ = make(strat_kw={"recompute": True})
    t = step_time(tr5, tokens)
    print(f"remat step: {t:.2f} ms")
    results["remat"] = {"step_ms": round(t, 2)}

    # last: profiler_block's enabled steps and extra phase compiles must
    # not perturb the ablation timing loops above (it also caps its own
    # errors, so telemetry never kills the JSON line)
    results["full"]["profiler"] = profiler_block(tr, (tokens,))

    print(json.dumps({"bench": "ablation_step", "batch": 8, "seq": 1024,
                      "configs": results}))


if __name__ == "__main__":
    main()
