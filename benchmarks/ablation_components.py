"""Scratch: MFU ablations on the real chip (not part of the framework)."""
import time, sys
import numpy as np
import jax, jax.numpy as jnp


def scalarize(r):
    leaves = jax.tree_util.tree_leaves(r)
    return sum(jnp.sum(jnp.abs(l.astype(jnp.float32))) if l.ndim else
               l.astype(jnp.float32) for l in leaves)


def timeit(f, *args, n=10):
    g = jax.jit(lambda *a: scalarize(f(*a)))
    float(np.asarray(g(*args)))   # compile + true sync (scalar fetch)
    t0 = time.perf_counter()
    for _ in range(n):
        r = g(*args)
    float(np.asarray(r))
    return (time.perf_counter() - t0) / n * 1e3


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.hybrid_gpt import GPTHybridTrainer
    from paddle_tpu.distributed.mesh import create_mesh
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.ops.flash_attention import _flash_mha
    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy_fn, shifted_labels

    paddle.seed(0)
    B, S, H, L, NH, V = 8, 1024, 768, 12, 12, 32768
    rng = np.random.RandomState(0)

    # 1. flash attention kernel alone (all layers' worth: L sequential calls)
    q = jnp.asarray(rng.randn(B, S, NH, 64).astype(np.float32)).astype(jnp.bfloat16)

    def attn_fwdbwd(q, k, v):
        def f(q, k, v):
            return _flash_mha(q, k, v, True, None).astype(jnp.float32).mean()
        l, g = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return l, g

    t = timeit(attn_fwdbwd, q, q, q)
    print(f"attention fwd+bwd one layer: {t:.3f} ms -> x{L} = {t*L:.1f} ms")

    # 2. fused CE alone
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32)).astype(jnp.bfloat16)
    w = jnp.asarray(rng.randn(V, H).astype(np.float32)).astype(jnp.bfloat16)
    tok = jnp.asarray(rng.randint(0, V, (B, S)).astype(np.int32))

    def ce_fwdbwd(x, w, tok):
        lab = shifted_labels(tok)
        return jax.value_and_grad(
            lambda x, w: fused_linear_cross_entropy_fn(x, w, lab, chunk=256),
            argnums=(0, 1))(x, w)

    t = timeit(ce_fwdbwd, x, w, tok)
    print(f"fused CE fwd+bwd: {t:.2f} ms")

    # 3. dense block matmuls alone (qkv+proj+mlp, L layers, fwd+bwd, bf16)
    w_qkv = jnp.asarray(rng.randn(L, H, 3*H).astype(np.float32)).astype(jnp.bfloat16)
    w_o = jnp.asarray(rng.randn(L, H, H).astype(np.float32)).astype(jnp.bfloat16)
    w_in = jnp.asarray(rng.randn(L, H, 4*H).astype(np.float32)).astype(jnp.bfloat16)
    w_out = jnp.asarray(rng.randn(L, 4*H, H).astype(np.float32)).astype(jnp.bfloat16)

    def mm_fwdbwd(x, ws):
        def f(x, ws):
            def body(h, w):
                wq, wo, wi, wo2 = w
                h = h + (h @ wq)[..., :H] @ wo
                h = h + jax.nn.gelu(h @ wi) @ wo2
                return h, None
            h, _ = jax.lax.scan(body, x, ws)
            return h.astype(jnp.float32).mean()
        return jax.value_and_grad(f)(x, ws)

    t = timeit(mm_fwdbwd, x, (w_qkv, w_o, w_in, w_out))
    print(f"dense matmuls (scan, {L} layers) fwd+bwd: {t:.2f} ms")

    # 4. embedding fwd+bwd (gather + scatter-add grad)
    def emb_fwdbwd(w, tok):
        def f(w):
            return w[tok].astype(jnp.float32).mean()
        return jax.value_and_grad(f)(w)

    t = timeit(emb_fwdbwd, w, tok)
    print(f"embedding gather+scatter bwd: {t:.2f} ms")

    # 5. optimizer-style update: adamw over 111M params (fp32 m/v/p + bf16 grad)
    P = 111_000_000
    p = jnp.zeros((P//1000, 1000), jnp.float32)
    m = jnp.zeros_like(p); v = jnp.zeros_like(p)
    g = jnp.zeros((P//1000, 1000), jnp.float32)

    def adam(p, m, v, g):
        m = 0.9*m + 0.1*g
        v = 0.999*v + 0.001*g*g
        return p - 1e-4*(m/(jnp.sqrt(v)+1e-8) + 0.01*p), m, v

    t = timeit(adam, p, m, v, g)
    print(f"adamw update {P/1e6:.0f}M params: {t:.2f} ms")

    # 6. full trainer step (reference point)
    cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,
                    num_heads=NH, max_seq_len=S)
    model = GPT(cfg)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    s = DistributedStrategy(); s.amp = True
    mesh = create_mesh({"dp": 1, "pp": 1, "tp": 1, "sp": 1}, jax.devices()[:1])
    tr = GPTHybridTrainer(model, opt, s, mesh, n_micro=1)
    tokens = rng.randint(0, V, (B, S)).astype(np.int32)
    float(np.asarray(tr.step(tokens)))
    t0 = time.perf_counter()
    for _ in range(10):
        loss = tr.step(tokens)
    float(np.asarray(loss))
    print(f"full step: {(time.perf_counter()-t0)/10*1e3:.2f} ms")


if __name__ == "__main__":
    main()
